"""Ulysses sequence parallelism: all-to-all head↔sequence resharding.

The DeepSpeed-Ulysses pattern (SURVEY §2.3 — not in torch core; its
primitive is `all_to_all`, torch:distributed/distributed_c10d.py:5145):
activations arrive sharded on the sequence dim over the ``'context'`` axis;
two ``lax.all_to_all``s swap that to head sharding around the attention
core, so each device computes FULL-sequence attention for S/n of the heads —
which lets the single-device Pallas flash kernel run unchanged inside the
manual region (ring attention by contrast restructures the kernel itself).

Tradeoff vs ring: all-to-all moves q+k+v+o once each (4·B·S·H·D/n per
device) instead of rotating k+v n-1 times; on an ICI torus both are
bandwidth-friendly, but Ulysses caps context parallelism at the head count
(H % n == 0) while ring scales to any n. Both are exposed behind
``MeshConfig.context_impl``.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
from jax.sharding import Mesh, PartitionSpec

from pytorch_distributed_train_tpu.ops import attention as attention_lib
from pytorch_distributed_train_tpu.utils.compat import shard_map

P = PartitionSpec


def ulysses_attention_local(
    q: jax.Array,  # (B, S_local, H, D) — seq-sharded on entry
    k: jax.Array,  # (B, S_local, Hkv, D)
    v: jax.Array,
    mask: jax.Array | None = None,  # (B, 1, Sq, Sk) FULL-seq, replicated
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = False,
    window: int = 0,
    impl: str = "auto",
) -> jax.Array:
    """Ulysses body — call inside shard_map. Returns seq-sharded output.

    all_to_all #1: (B, S/n, H, D) → (B, S, H/n, D)  [scatter heads, gather seq]
    local attention over the full sequence with H/n heads
    all_to_all #2: back to (B, S/n, H, D).

    Unlike ring attention, an arbitrary (e.g. padding) mask just works: after
    the first all_to_all every device sees the full sequence, so the
    replicated full-seq mask applies unchanged (this is why BERT-style padded
    batches route here — ops.attention dispatch).
    """
    from pytorch_distributed_train_tpu.ops.cp_common import expand_kv_heads

    n = axis_size
    if n == 1:
        return attention_lib.dot_product_attention(q, k, v, causal=causal,
                                                   mask=mask, impl=impl,
                                                   window=window)
    H, Hkv = q.shape[2], k.shape[2]
    if H % n != 0:
        raise ValueError(f"ulysses needs heads {H} % context {n} == 0")
    if Hkv != H and Hkv % n != 0:
        # GQA ratio the axis can't divide — expand before the swap (pays
        # H/Hkv extra ICI bytes; unavoidable for this head count).
        k, v = expand_kv_heads(k, v, H)

    # split_axis=2 (heads scattered), concat_axis=1 (seq gathered)
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    q, k, v = a2a(q), a2a(k), a2a(v)
    # GQA with Hkv % n == 0: K/V crossed the wire at Hkv/n heads — the
    # H/Hkv-fold expansion happens here, after the transfer, for free in
    # compute (XLA fuses the broadcast) and at zero extra ICI traffic.
    k, v = expand_kv_heads(k, v, q.shape[2])
    # After the swap each device holds the FULL sequence (for H/n
    # heads), so the sliding window applies directly on the local core.
    o = attention_lib.dot_product_attention(q, k, v, causal=causal, mask=mask,
                                            impl=impl, window=window)
    # inverse: scatter seq, gather heads
    return jax.lax.all_to_all(o, axis_name=axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,  # (B, S, H, D) GLOBAL
    k: jax.Array,
    v: jax.Array,
    *,
    mask: jax.Array | None = None,  # (B, 1, Sq, Sk) or broadcastable
    mesh: Mesh,
    causal: bool = False,
    window: int = 0,
    context_axis: str = "context",
    batch_axes: Sequence[str] = ("data", "fsdp"),
    tensor_axis: str | None = "tensor",
    impl: str = "auto",
) -> jax.Array:
    """Global-array shard_map wrapper (mirror of ring_attention's)."""
    from pytorch_distributed_train_tpu.ops.cp_common import (
        divisible_axes,
        qkv_spec,
    )

    n = mesh.shape[context_axis]
    if q.shape[1] % n != 0 or k.shape[1] % n != 0:
        return attention_lib.dot_product_attention(q, k, v, causal=causal,
                                                   mask=mask, impl=impl,
                                                   window=window)
    spec = qkv_spec(q, k, mesh, context_axis=context_axis,
                    batch_axes=batch_axes, tensor_axis=tensor_axis)
    fn = functools.partial(
        ulysses_attention_local, axis_name=context_axis, axis_size=n,
        causal=causal, window=window, impl=impl,
    )
    if mask is None:
        return shard_map(
            lambda a, b, c: fn(a, b, c),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)
    # Mask stays full-seq: sharded on batch only, replicated over context.
    mask_spec = P(divisible_axes(mask.shape[0], batch_axes, mesh),
                  *([None] * (mask.ndim - 1)))
    return shard_map(
        lambda a, b, c, m: fn(a, b, c, m),
        mesh=mesh, in_specs=(spec, spec, spec, mask_spec), out_specs=spec,
        check_vma=False,
    )(q, k, v, mask)
