"""Audit-driven fused epilogues: the compute-side answer to PR 9's
``perf_ledger --audit`` kernel-gap report (ROADMAP item 2).

Two families of fusion live here, both measured against an unfused
reference that stays in the tree as the semantics oracle:

1. **Fused optimizer epilogue** (:class:`FusedEpilogue`): clip-by-
   global-norm + optimizer update + non-finite gate computed in ONE
   pass over the gradient tree. The optax chain built by
   ``optim.make_optimizer`` does the same work as three sequential tree
   traversals (clip → per-transform update → apply_updates) plus — when
   the sentinel gate is on — a whole-TrainState two-branch select that
   materializes the stepped AND skipped trees. Here every leaf computes
   ``new = where(finite, f(clip(g), mu, nu, p), old)`` inline, so XLA
   emits one fused read-modify-write per parameter instead of bouncing
   the grad tree through HBM between chain links. Numerics are
   REPLICATED from the installed optax (same op order, same dtypes,
   same ``safe_int32_increment`` counter semantics) and pinned by
   tests against the chain — bit-for-bit, LR-cooldown leaf included.
   The produced ``opt_state`` keeps the chain's exact pytree structure,
   so checkpoints, the sentinel's cooldown rewind, and the partition
   rules are oblivious to which path ran.

2. **Fused model-block epilogues** (:class:`FusedDenseGelu`,
   :class:`FusedResidualLayerNorm`): the top *elementwise* entries of
   the kernel-gap audit for the transformer presets — the MLP's
   bias+GELU chain and (post-LN BERT) the residual-add+LayerNorm
   chain — expressed as single tagged expressions. Param names, init
   and math match the ``nn.Dense``/``nn.LayerNorm`` formulation
   exactly (checkpoints interchange); the new thing is the
   ``checkpoint_name`` tag (:data:`FUSED_EPILOGUE_NAME`), which gives
   the remat policy layer (models/remat.py ``no_fused_epilogue``) a
   handle to recompute exactly these cheap chains in backward instead
   of saving their activations — the flops↔HBM dial the audit's
   elementwise gap asks for.

The jitted-step purity contract applies (tools/analyze jit-purity pass
covers this file): everything here is traced math — no host syncs, no
prints, no wall clocks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

# Tag on fused-epilogue outputs: remat policies key on it
# (jax.checkpoint_policies.save_any_names_but_these — models/remat.py).
FUSED_EPILOGUE_NAME = "fused_epilogue"


# ---------------------------------------------------------------------------
# Model-block epilogues
# ---------------------------------------------------------------------------


def bias_gelu(y: jax.Array, bias: jax.Array) -> jax.Array:
    """bias-add + exact-erf GELU as one tagged elementwise chain.

    Same math as ``Dense``'s ``y + bias`` followed by
    ``nn.gelu(..., approximate=False)`` — the tag, not the arithmetic,
    is the point: remat can now name-drop this output."""
    y = y + jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))
    y = nn.gelu(y, approximate=False)
    return checkpoint_name(y, FUSED_EPILOGUE_NAME)


class FusedDenseGelu(nn.Module):
    """``nn.Dense`` + exact GELU with the epilogue fused and tagged.

    Param-compatible with ``nn.Dense(features, name=...)`` — same
    ``kernel``/``bias`` names, shapes, initializers and dtype promotion
    (flax's own ``promote_dtype``), so checkpoints and partition rules
    are unchanged and the fused/unfused arms share weights bit-for-bit.
    """

    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (jnp.shape(x)[-1], self.features), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,), self.param_dtype)
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype)
        y = jax.lax.dot_general(
            x, kernel, (((x.ndim - 1,), (0,)), ((), ())))
        return bias_gelu(y, bias)


class FusedResidualLayerNorm(nn.Module):
    """residual-add + LayerNorm as one tagged fp32 chain (post-LN BERT's
    ``ln(x + h)`` epilogue).

    Replicates flax ``nn.LayerNorm``'s numerics exactly — fast-variance
    statistics promoted to fp32, ``x - mean`` then ``rsqrt(var + eps) *
    scale`` then ``+ bias`` in that order, fp32 output — with the same
    ``scale``/``bias`` param names under this module's own name, so
    swapping ``ln(name)(x + h)`` for ``FusedResidualLayerNorm(name=
    name)(h, x)`` preserves the param tree and the bits."""

    epsilon: float = 1e-12
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, residual):
        y = x + residual  # compute-dtype residual add (as `x + h` was)
        stats_dtype = jnp.promote_types(jnp.result_type(y), jnp.float32)
        yf = jnp.asarray(y, stats_dtype)
        mean = yf.mean(-1)
        mean2 = (yf * yf).mean(-1)
        var = jnp.maximum(0.0, mean2 - mean * mean)
        feat = y.shape[-1]
        scale = self.param("scale", nn.initializers.ones_init(),
                           (feat,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (feat,), self.param_dtype)
        out = y - mean[..., None]
        mul = jax.lax.rsqrt(var + self.epsilon)[..., None] * scale
        out = out * mul + bias
        return checkpoint_name(jnp.asarray(out, jnp.float32),
                               FUSED_EPILOGUE_NAME)


# ---------------------------------------------------------------------------
# Fused optimizer epilogue
# ---------------------------------------------------------------------------


def _safe_int32_increment(count):
    # optax.numerics.safe_int32_increment — replicated so the fused
    # counter can never disagree with the chain's at int32 saturation.
    max_i32 = jnp.iinfo(jnp.int32).max
    return jnp.where(count < max_i32,
                     count + jnp.array(1, jnp.int32), max_i32)


@dataclasses.dataclass(frozen=True)
class FusedEpilogue:
    """One-pass clip + optimizer update + gate, oracle'd by the optax
    chain ``optim.make_optimizer`` builds for the same config.

    Built by ``optim.make_fused_update`` (the ``make_optimizer`` fast
    path), which first proves the config expressible
    (``optim.fused_update_unsupported_reason``). ``kind`` selects the
    per-leaf math; the chain-state layout is derived from the same
    booleans make_optimizer used to assemble its parts list, so the
    returned ``opt_state`` is structurally identical to the chain's.

    Call: ``new_params, new_opt_state, grad_norm = fe(grads, opt_state,
    params, finite=...)``. ``finite=None`` means ungated; a traced bool
    scalar folds the sentinel/GradScaler skip into the same pass —
    every leaf (params, moments, counters) selects its OLD value when
    the step is judged non-finite, matching the chain path's
    whole-tree ``jnp.where`` select.
    """

    kind: str                      # adamw | adam | sgd
    sched: Callable                # schedule: count -> lr
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    momentum: float | None = None  # sgd only
    nesterov: bool = False
    clip_norm: float = 0.0         # 0 = no clip link in the chain
    cooldown: bool = False         # sentinel LR-cooldown link present
    mu_dtype: Any = None           # adam mu / sgd trace storage dtype
    mask: Callable | None = None   # decay mask fn (params -> bool tree)

    # ---------------------------------------------------- state layout
    def _indices(self) -> dict:
        """Chain-state tuple indices, mirroring make_optimizer's parts
        order: [clip?] [coupled wd?] [optimizer] [cooldown?]."""
        idx = 0
        out = {}
        if self.clip_norm > 0:
            out["clip"] = idx
            idx += 1
        if self.kind in ("sgd", "adam") and self.weight_decay > 0:
            out["wd"] = idx  # add_decayed_weights link (coupled L2)
            idx += 1
        out["opt"] = idx
        idx += 1
        if self.cooldown:
            out["cooldown"] = idx
        return out

    def _mask_tree(self, params):
        if self.mask is None:
            return jax.tree.map(lambda _: True, params)
        return self.mask(params)

    # --------------------------------------------------------- update
    def __call__(self, grads, opt_state, params, finite=None):
        import optax

        ix = self._indices()
        opt_inner = opt_state[ix["opt"]]
        cooldown_scale = (opt_state[ix["cooldown"]].scale
                          if self.cooldown else None)

        gnorm = optax.global_norm(grads)
        if self.clip_norm > 0:
            trigger = gnorm < self.clip_norm

            def clip_leaf(t):
                # optax.clip_by_global_norm's exact formulation
                return jax.lax.select(
                    trigger, t, (t / gnorm.astype(t.dtype)) * self.clip_norm)
        else:
            clip_leaf = lambda t: t  # noqa: E731

        def gate(new, old):
            if finite is None:
                return new
            return jnp.where(finite, new, old)

        def lr_mul(u, sched_count):
            # scale_by_schedule: updates * jnp.array(-lr, u.dtype)
            return u * jnp.array(-self.sched(sched_count), dtype=u.dtype)

        def cool(u):
            if cooldown_scale is None:
                return u
            return u * cooldown_scale.astype(u.dtype)

        mask_tree = self._mask_tree(params)

        if self.kind in ("adamw", "adam"):
            if self.kind == "adamw":
                adam_st, wd_st, sched_st = opt_inner
            else:
                adam_st, sched_st = opt_inner
                wd_st = opt_state[ix["wd"]] if "wd" in ix else None
            count_inc = _safe_int32_increment(adam_st.count)
            b1c = 1 - self.b1 ** count_inc  # tree_bias_correction
            b2c = 1 - self.b2 ** count_inc
            sched_count = sched_st.count
            wd = self.weight_decay

            def leaf(g, p, mu, nu, decay):
                g = clip_leaf(g)
                if self.kind == "adam" and wd > 0 and decay:
                    g = g + wd * p  # coupled L2 BEFORE the moments
                mu_n = (1 - self.b1) * g + self.b1 * mu
                nu_n = (1 - self.b2) * (g ** 2) + self.b2 * nu
                mu_hat = mu_n / b1c.astype(mu_n.dtype)
                nu_hat = nu_n / b2c.astype(nu_n.dtype)
                u = mu_hat / (jnp.sqrt(nu_hat + 0.0) + self.eps)
                mu_store = (mu_n.astype(self.mu_dtype)
                            if self.mu_dtype is not None else mu_n)
                if self.kind == "adamw" and wd > 0 and decay:
                    u = u + wd * p  # decoupled decay AFTER the moments
                u = cool(lr_mul(u, sched_count))
                new_p = jnp.asarray(p + u).astype(jnp.asarray(p).dtype)
                return (gate(new_p, p), gate(mu_store, mu),
                        gate(nu_n, nu))

            fused = jax.tree.map(leaf, grads, params,
                                 adam_st.mu, adam_st.nu, mask_tree)
            new_params = jax.tree.map(lambda t: t[0], fused,
                                      is_leaf=lambda t: isinstance(t, tuple))
            new_mu = jax.tree.map(lambda t: t[1], fused,
                                  is_leaf=lambda t: isinstance(t, tuple))
            new_nu = jax.tree.map(lambda t: t[2], fused,
                                  is_leaf=lambda t: isinstance(t, tuple))
            new_adam = optax.ScaleByAdamState(
                count=gate(count_inc, adam_st.count), mu=new_mu, nu=new_nu)
            new_sched = optax.ScaleByScheduleState(
                count=gate(_safe_int32_increment(sched_st.count),
                           sched_st.count))
            if self.kind == "adamw":
                new_inner = (new_adam, wd_st, new_sched)
            else:
                new_inner = (new_adam, new_sched)
        else:  # sgd / momentum
            trace_st, sched_st = opt_inner
            sched_count = sched_st.count
            wd = self.weight_decay
            has_trace = self.momentum is not None
            mu_tree = trace_st.trace if has_trace else params  # dummy

            def leaf(g, p, tr, decay):
                g = clip_leaf(g)
                if wd > 0 and decay:
                    g = g + wd * p  # torch-coupled L2 before momentum
                if has_trace:
                    tr_n = g + self.momentum * tr
                    u = g + self.momentum * tr_n if self.nesterov else tr_n
                    tr_store = (tr_n.astype(self.mu_dtype)
                                if self.mu_dtype is not None else tr_n)
                else:
                    u, tr_store = g, tr
                u = cool(lr_mul(u, sched_count))
                new_p = jnp.asarray(p + u).astype(jnp.asarray(p).dtype)
                return (gate(new_p, p), gate(tr_store, tr))

            fused = jax.tree.map(leaf, grads, params, mu_tree, mask_tree)
            new_params = jax.tree.map(lambda t: t[0], fused,
                                      is_leaf=lambda t: isinstance(t, tuple))
            if has_trace:
                new_trace = jax.tree.map(
                    lambda t: t[1], fused,
                    is_leaf=lambda t: isinstance(t, tuple))
                new_inner = (type(trace_st)(trace=new_trace),
                             optax.ScaleByScheduleState(
                                 count=gate(
                                     _safe_int32_increment(sched_st.count),
                                     sched_st.count)))
            else:
                new_inner = (trace_st,
                             optax.ScaleByScheduleState(
                                 count=gate(
                                     _safe_int32_increment(sched_st.count),
                                     sched_st.count)))

        new_state = list(opt_state)
        new_state[ix["opt"]] = new_inner
        return new_params, tuple(new_state), gnorm
