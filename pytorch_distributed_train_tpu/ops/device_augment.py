"""Device-side image augmentation inside the jitted train step (ISSUE 12c).

PR 9's staged attribution showed the native input preset spending 79%
of its host wall in *augment* — crop/flip/normalize (and RandAugment
when on) running on host cores that should be feeding the chip. This
module moves that work into the jitted step, next to MixUp
(ops/mixup.py, the existing device-side batch transform): the host
ships RAW uint8 pixels (4x less h2d traffic than normalized f32), and
the augment collapses into a few fused elementwise passes on a batch
already resident in HBM.

PRNG discipline — identical to dropout's: the step folds its base key
by the step counter, then folds a constant domain tag for the augment
(steps.py), so draws are deterministic under resume (same step -> same
crops), no key chain is checkpointed, and augment draws can never
collide with dropout/mixup streams. Per-image draws come from one
``jax.random`` call per decision vector (no per-image key splitting).

Semantics:

- **crop/flip/normalize** (array-style datasets): reflect-101 pad +
  random crop + horizontal flip + (x/255 - mean)/std — the SAME
  arithmetic as the host paths (datasets._crop_flip / native imgops),
  exposed as the pure kernel :func:`crop_flip_normalize` so the
  host/device equivalence is testable with shared draws
  (tests/test_zinput_plane.py). Item-style decode datasets keep
  RandomResizedCrop host-side (it is decode-adjacent resampling) and
  move flip/RandAugment/normalize here.
- **RandAugment** (``data.randaugment_num_ops > 0``): the torchvision
  op TABLE (14 ops, 31 magnitude bins, signed-op coin flip — mirroring
  data/augment.py) reimplemented on uint8 tensors. Photometric ops
  (brightness/color/contrast/sharpness/posterize/solarize/autocontrast/
  equalize) match PIL semantics closely; geometric ops (shear/translate/
  rotate) use NEAREST resampling via an inverse-affine gather, like
  torchvision's InterpolationMode.NEAREST default. The op space is the
  same; per-op pixel results are NOT bit-identical to the PIL chain
  (different resampling internals) — documented in docs/performance.md.
  Each op is applied batch-wide under a per-image selection mask: 14
  cheap elementwise passes beat a 14-way vmap'd switch on TPU.

Everything here is shape-static and host-sync-free (jit-purity pass
scope includes this module): Python branches only on config fields and
dtypes, never on traced values.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

_BINS = 31  # torchvision magnitude binning (data/augment.py mirrors it)


# --------------------------------------------------------------- kernels

def crop_flip_u8(images_u8, ys, xs, flips, pad: int) -> jnp.ndarray:
    """Reflect-pad random crop + hflip on uint8, draws PASSED IN — the
    one definition of the device crop kernel (semantics ==
    datasets._crop_flip: reflect-101 padding)."""
    x = jnp.asarray(images_u8)
    B, H, W, C = x.shape
    if pad > 0:
        x = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")

        def one(im, y, xo):
            return jax.lax.dynamic_slice(im, (y, xo, 0), (H, W, C))

        x = jax.vmap(one)(x, jnp.asarray(ys, jnp.int32),
                          jnp.asarray(xs, jnp.int32))
    return jnp.where(jnp.asarray(flips, bool)[:, None, None, None],
                     x[:, :, ::-1, :], x)


def crop_flip_normalize(images_u8, ys, xs, flips, pad: int,
                        mean, std) -> jnp.ndarray:
    """crop_flip_u8 + u8->f32 normalize — the host-equivalence test
    surface (== datasets._crop_flip then (x/255 - mean)/std)."""
    return normalize_u8(crop_flip_u8(images_u8, ys, xs, flips, pad),
                        mean, std)


def normalize_u8(images_u8, mean, std) -> jnp.ndarray:
    """(x/255 - mean)/std in float32 — the eval-path transform and the
    tail of every train path."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    return (images_u8.astype(jnp.float32) / 255.0 - mean) / std


def _to_u8(x) -> jnp.ndarray:
    return jnp.clip(jnp.round(x), 0.0, 255.0).astype(jnp.uint8)


def _gray(x_f32) -> jnp.ndarray:
    """ITU-R 601-2 luma, PIL's L-mode weights (keepdims channel)."""
    w = jnp.asarray([0.299, 0.587, 0.114], jnp.float32)
    return jnp.sum(x_f32 * w, axis=-1, keepdims=True)


def _blend(a, b, factor):
    """PIL ImageEnhance blend: a + factor*(b - a), factor (B,)-shaped."""
    f = factor[:, None, None, None]
    return a + f * (b - a)


def _affine_nearest(x_u8, mat) -> jnp.ndarray:
    """Per-image inverse-affine resample with NEAREST sampling, zero
    fill — the PIL ``Image.transform(AFFINE, NEAREST, fillcolor=0)``
    analogue. ``mat`` is (B, 6): x_src = a*x + b*y + c, y_src = d*x +
    e*y + f (PIL's coefficient convention)."""
    B, H, W, C = x_u8.shape
    ys, xs = jnp.mgrid[0:H, 0:W]

    def one(im, m):
        a, b_, c, d, e, f = m
        sx = jnp.round(a * xs + b_ * ys + c).astype(jnp.int32)
        sy = jnp.round(d * xs + e * ys + f).astype(jnp.int32)
        ok = (sx >= 0) & (sx < W) & (sy >= 0) & (sy < H)
        gathered = im[jnp.clip(sy, 0, H - 1), jnp.clip(sx, 0, W - 1)]
        return jnp.where(ok[..., None], gathered, jnp.uint8(0))

    return jax.vmap(one)(x_u8, mat)


def _identity_mat(B):
    return jnp.tile(jnp.asarray([1.0, 0.0, 0.0, 0.0, 1.0, 0.0],
                                jnp.float32), (B, 1))


# Photometric ops: (B,H,W,C) u8 + (B,) magnitude -> u8.

def _op_brightness(x, mag):
    f = x.astype(jnp.float32)
    return _to_u8(_blend(jnp.zeros_like(f), f, 1.0 + mag))


def _op_color(x, mag):
    f = x.astype(jnp.float32)
    return _to_u8(_blend(jnp.broadcast_to(_gray(f), f.shape), f, 1.0 + mag))


def _op_contrast(x, mag):
    f = x.astype(jnp.float32)
    # PIL Contrast degenerate point: the mean of the L-mode image,
    # rounded (ImageEnhance uses ImageStat on the grayscale).
    m = jnp.round(jnp.mean(_gray(f), axis=(1, 2, 3), keepdims=True))
    return _to_u8(_blend(jnp.broadcast_to(m, f.shape), f, 1.0 + mag))


def _op_sharpness(x, mag):
    f = x.astype(jnp.float32)
    # PIL SMOOTH kernel: 3x3 [[1,1,1],[1,5,1],[1,1,1]]/13, edges kept.
    k = jnp.asarray([[1., 1., 1.], [1., 5., 1.], [1., 1., 1.]]) / 13.0
    blurred = jax.lax.conv_general_dilated(
        f.transpose(0, 3, 1, 2).reshape(-1, 1, *f.shape[1:3]),
        k[None, None], (1, 1), "SAME")
    blurred = blurred.reshape(f.shape[0], f.shape[3],
                              *f.shape[1:3]).transpose(0, 2, 3, 1)
    # PIL keeps the 1-pixel border unfiltered.
    border = jnp.zeros(f.shape[1:3], bool).at[1:-1, 1:-1].set(True)
    blurred = jnp.where(border[None, :, :, None], blurred, f)
    return _to_u8(_blend(blurred, f, 1.0 + mag))


def _op_posterize(x, mag):
    bits = mag.astype(jnp.int32)  # bits to KEEP
    mask = (0xFF00 >> bits).astype(jnp.uint8)  # 8-bit mask, high bits kept
    return x & mask[:, None, None, None]


def _op_solarize(x, mag):
    thresh = mag[:, None, None, None]
    return jnp.where(x.astype(jnp.float32) >= thresh, 255 - x, x)


def _op_autocontrast(x, _mag):
    f = x.astype(jnp.float32)
    lo = jnp.min(f, axis=(1, 2), keepdims=True)
    hi = jnp.max(f, axis=(1, 2), keepdims=True)
    scale = 255.0 / jnp.maximum(hi - lo, 1.0)
    out = (f - lo) * scale
    return jnp.where(hi > lo, _to_u8(out), x)


def _op_equalize(x, _mag):
    # PIL ImageOps.equalize: per-channel histogram LUT with the
    # nonzero-step convention.
    def one_channel(ch):  # (H, W) u8
        hist = jnp.zeros(256, jnp.int32).at[ch.reshape(-1)].add(1)
        nonzero = hist > 0
        last = jnp.max(jnp.where(nonzero, jnp.arange(256), -1))
        step = (jnp.sum(hist) - hist[last]) // 255
        cum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(hist)[:-1]])
        lut = (cum + step // 2) // jnp.maximum(step, 1)
        lut = jnp.clip(lut, 0, 255).astype(jnp.uint8)
        return jnp.where(step == 0, ch, lut[ch])

    return jax.vmap(jax.vmap(one_channel, in_axes=-1, out_axes=-1))(x)


def _op_shear_x(x, mag):
    B = x.shape[0]
    m = _identity_mat(B).at[:, 1].set(mag)
    return _affine_nearest(x, m)


def _op_shear_y(x, mag):
    B = x.shape[0]
    m = _identity_mat(B).at[:, 3].set(mag)
    return _affine_nearest(x, m)


def _op_translate_x(x, mag):
    B = x.shape[0]
    m = _identity_mat(B).at[:, 2].set(mag)
    return _affine_nearest(x, m)


def _op_translate_y(x, mag):
    B = x.shape[0]
    m = _identity_mat(B).at[:, 5].set(mag)
    return _affine_nearest(x, m)


def _op_rotate(x, mag):
    # rotate about the image center by mag degrees (inverse mapping).
    B, H, W, _ = x.shape
    rad = -mag * jnp.pi / 180.0  # inverse rotation
    cos, sin = jnp.cos(rad), jnp.sin(rad)
    cx, cy = (W - 1) / 2.0, (H - 1) / 2.0
    a, b = cos, -sin
    d, e = sin, cos
    c = cx - a * cx - b * cy
    f = cy - d * cx - e * cy
    return _affine_nearest(x, jnp.stack([a, b, c, d, e, f], axis=-1))


def _op_identity(x, _mag):
    return x


def _magnitude_table(height: int, width: int) -> list:
    """(name, fn, magnitudes[31] | None, signed) — index-aligned with
    data/augment.py's host table so op draws mean the same thing."""
    lin = np.linspace
    return [
        ("Identity", _op_identity, None, False),
        ("ShearX", _op_shear_x, lin(0.0, 0.3, _BINS), True),
        ("ShearY", _op_shear_y, lin(0.0, 0.3, _BINS), True),
        ("TranslateX", _op_translate_x,
         lin(0.0, 150.0 / 331.0 * width, _BINS), True),
        ("TranslateY", _op_translate_y,
         lin(0.0, 150.0 / 331.0 * height, _BINS), True),
        ("Rotate", _op_rotate, lin(0.0, 30.0, _BINS), True),
        ("Brightness", _op_brightness, lin(0.0, 0.9, _BINS), True),
        ("Color", _op_color, lin(0.0, 0.9, _BINS), True),
        ("Contrast", _op_contrast, lin(0.0, 0.9, _BINS), True),
        ("Sharpness", _op_sharpness, lin(0.0, 0.9, _BINS), True),
        ("Posterize", _op_posterize,
         8 - np.round(np.arange(_BINS) / ((_BINS - 1) / 4)), False),
        ("Solarize", _op_solarize, lin(255.0, 0.0, _BINS), False),
        ("AutoContrast", _op_autocontrast, None, False),
        ("Equalize", _op_equalize, None, False),
    ]


def randaugment_u8(images_u8, rng, num_ops: int,
                   magnitude: int) -> jnp.ndarray:
    """Device RandAugment: ``num_ops`` rounds; each round draws one op
    index + sign per image and applies every table op batch-wide under
    the per-image selection mask."""
    x = jnp.asarray(images_u8)
    B, H, W, _ = x.shape
    table = _magnitude_table(H, W)
    for round_i in range(num_ops):
        r = jax.random.fold_in(rng, round_i)
        r_op, r_sign = jax.random.split(r)
        op_idx = jax.random.randint(r_op, (B,), 0, len(table))
        neg = jax.random.bernoulli(r_sign, 0.5, (B,))
        for k, (_name, fn, mags, signed) in enumerate(table):
            base = float(mags[magnitude]) if mags is not None else 0.0
            mag = jnp.full((B,), base, jnp.float32)
            if signed:
                mag = jnp.where(neg, -mag, mag)
            sel = (op_idx == k)[:, None, None, None]
            x = jnp.where(sel, fn(x, mag), x)
    return x


# ------------------------------------------------------------- transform

@dataclass(frozen=True)
class DeviceAugment:
    """Batch transform: (batch, rng, train) -> batch with augmented,
    normalized f32 images. All fields static (closed over by the jitted
    step — ops/mixup.py's pattern). Batches whose images are NOT uint8
    pass through untouched: that is the contract with datasets that
    cannot ship raw u8 (synthetic f32, native-decode tar) — their
    pixels arrive already normalized and must not be double-processed.
    """

    mean: tuple = ()
    std: tuple = ()
    pad: int = 4              # reflect-pad crop margin; 0 = no crop
    crop: bool = True         # False for item-style (RRC stayed host-side)
    flip: bool = True
    randaugment_num_ops: int = 0
    randaugment_magnitude: int = 9

    def __call__(self, batch: dict, rng, train: bool = True) -> dict:
        images = batch.get("image")
        if images is None or images.dtype != jnp.uint8:
            return batch
        B = images.shape[0]
        if not train:
            out = dict(batch)
            out["image"] = normalize_u8(images, self.mean, self.std)
            return out
        # torchvision order on u8 throughout: crop -> flip ->
        # RandAugment -> normalize (normalize is always last, so the
        # whole u8 chain fuses under jit).
        r_crop, r_flip, r_ra = jax.random.split(rng, 3)
        flips = (jax.random.bernoulli(r_flip, 0.5, (B,))
                 if self.flip else jnp.zeros((B,), bool))
        if self.crop and self.pad > 0:
            offs = jax.random.randint(r_crop, (B, 2), 0, 2 * self.pad + 1)
            x = crop_flip_u8(images, offs[:, 0], offs[:, 1], flips,
                             self.pad)
        else:
            x = jnp.where(flips[:, None, None, None],
                          images[:, :, ::-1, :], images)
        if self.randaugment_num_ops > 0:
            x = randaugment_u8(x, r_ra, self.randaugment_num_ops,
                               self.randaugment_magnitude)
        out = dict(batch)
        out["image"] = normalize_u8(x, self.mean, self.std)
        return out


def build_device_augment(data_cfg, dataset) -> DeviceAugment | None:
    """Config + dataset -> transform (or None when off / inapplicable).

    The dataset decides applicability: only one that ships raw u8
    (``raw_u8`` attribute — U8ImageDataset family, packed cache,
    ImageFolder/tar PIL paths) gets the device transform; its mean/std
    ride along so host and device normalize with identical constants.
    """
    if not getattr(data_cfg, "device_augment", False):
        return None
    if not getattr(dataset, "raw_u8", False):
        import sys

        print("[device-augment] data.device_augment is on but dataset "
              f"{type(dataset).__name__} cannot ship raw u8 pixels — "
              "host path unchanged", file=sys.stderr, flush=True)
        return None
    from pytorch_distributed_train_tpu.data.datasets import (
        IMAGENET_MEAN,
        IMAGENET_STD,
    )

    mean = np.asarray(getattr(dataset, "mean", IMAGENET_MEAN), np.float32)
    std = np.asarray(getattr(dataset, "std", IMAGENET_STD), np.float32)
    item_style = bool(getattr(dataset, "is_item_style", False))
    return DeviceAugment(
        mean=tuple(float(v) for v in mean),
        std=tuple(float(v) for v in std),
        pad=int(getattr(dataset, "pad", 4)),
        crop=not item_style,  # item-style: RRC already happened host-side
        flip=True,
        randaugment_num_ops=int(getattr(data_cfg, "randaugment_num_ops",
                                        0)),
        randaugment_magnitude=int(getattr(data_cfg,
                                          "randaugment_magnitude", 9)),
    )
