"""Pallas TPU flash attention: fused online-softmax attention, fwd + bwd.

The TPU counterpart of the reference stack's fused attention kernels
(torch SDPA/cuDNN flash path — SURVEY C23): never materialises the (S, S)
score matrix in HBM. Forward keeps per-row running max/sum accumulators in
VMEM and streams KV blocks through the MXU (the flash-attention-2
formulation); backward recomputes P per block from the saved logsumexp and
accumulates dQ / dK / dV in two kernels.

Layout: inputs (B, S, H, D) are reshaped to (B·H, S, D); the kernel grid is
(B·H, S/block_q) with an inner arbitrary-order sweep over S/block_k. D must
be 64/128/256 (lane-aligned); S must divide by the block sizes. Softmax math
is fp32 regardless of input dtype (matches ops.attention policy).

Causal masking skips whole KV blocks above the diagonal (no wasted MXU work)
and applies an iota mask only on diagonal blocks.

Enable/disable: dispatched from ops.attention.dot_product_attention; tests
run interpret=True on CPU against the XLA reference implementation
(SURVEY §5.2 "Pallas kernels → interpret=True mode vs XLA reference").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Tuned on TPU v5e (S=2048, D=128, bf16): large tiles amortize per-program
# overhead — 128x128 ran ~3.5x slower than 512x1024. VMEM check: the f32
# score tile is block_q x block_k x 4B = 2 MB, well inside the ~16 MB budget
# with q/k/v/acc blocks.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def supported(q, k, v, *, causal: bool, mask) -> bool:
    if mask is not None:
        return False
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if Sq != Sk:  # self-attention only (no KV-cache decode shapes)
        return False
    if D not in (64, 128, 256):
        return False
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H and (Hkv == 0 or H % Hkv != 0):
        return False  # invalid GQA ratio — let the XLA path raise clearly
    bq = min(DEFAULT_BLOCK_Q, Sq)
    bk = min(DEFAULT_BLOCK_K, Sk)
    return Sq % bq == 0 and Sk % bk == 0 and bq % 8 == 0 and bk % 128 == 0


def profitable(q) -> bool:
    # Below ~1k tokens XLA's fused attention is already fine; flash pays off
    # when the score matrix stops fitting in VMEM.
    return q.shape[1] >= 1024


# ================================================================= forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, block_q, block_k,
                causal, scale):
    """Grid (BH, nq, nk): one (block_q, D) output tile, sweeping KV blocks."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: KV block strictly above the diagonal contributes nothing.
    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32)  # (block_q, D)
        kb = k_ref[0].astype(jnp.float32)  # (block_k, D)
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            causal_mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(causal_mask, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # (block_q, block_k)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _body()
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(l_safe)


def _fwd(q3, k3, v3, *, causal, scale, block_q, block_k, interpret):
    BH, S, D = q3.shape
    nq, nk = S // block_q, S // block_k
    grid = (BH, nq, nk)
    out_shape = [
        jax.ShapeDtypeStruct(q3.shape, q3.dtype),  # O
        jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),  # LSE (trailing 1: TPU block-shape alignment)
    ]
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k,
        causal=causal, scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3)


# ================================================================ backward
#
# flash2 backward: with P = exp(S - lse) and delta_i = rowsum(dO_i * O_i):
#   dV_j = sum_i P_ij^T dO_i
#   dP_ij = dO_i V_j^T
#   dS_ij = P_ij * (dP_ij - delta_i)
#   dQ_i = scale * sum_j dS_ij K_j
#   dK_j = scale * sum_i dS_ij^T Q_i

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, block_q, block_k, causal, scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # (block_q, 1)
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where((q_start + rows) >= (k_start + cols), s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _body()
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _fin():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, block_q, block_k, causal, scale):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # (block_q, 1)
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where((q_start + rows) >= (k_start + cols), s, NEG_INF)
        p = jnp.exp(s - lse)  # (block_q, block_k)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_k, D)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_k, D)

    if causal:
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _body()
    else:
        _body()

    @pl.when(qi == nq - 1)
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, lse, do3, *, causal, scale, block_q, block_k,
         interpret):
    BH, S, D = q3.shape
    nq, nk = S // block_q, S // block_k
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)[..., None]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ============================================================== public API

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q3, k3, v3, causal, scale, block_sizes, interpret):
    o, _ = _fwd(q3, k3, v3, causal=causal, scale=scale,
                block_q=block_sizes[0], block_k=block_sizes[1],
                interpret=interpret)
    return o


def _flash_fwd(q3, k3, v3, causal, scale, block_sizes, interpret):
    o, lse = _fwd(q3, k3, v3, causal=causal, scale=scale,
                  block_q=block_sizes[0], block_k=block_sizes[1],
                  interpret=interpret)
    return o, (q3, k3, v3, o, lse)


def _flash_bwd(causal, scale, block_sizes, interpret, res, do3):
    q3, k3, v3, o3, lse = res
    dq, dk, dv = _bwd(q3, k3, v3, o3, lse, do3, causal=causal, scale=scale,
                      block_q=block_sizes[0], block_k=block_sizes[1],
                      interpret=interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """(B, S, H, D) attention via the Pallas kernel. GQA callers must repeat
    KV heads first (ops.attention does)."""
    if q.shape[2] != k.shape[2] or k.shape != v.shape:
        raise ValueError(
            f"flash_attention needs pre-expanded KV heads: q {q.shape}, "
            f"k {k.shape}, v {v.shape}"
        )
    B, S, H, D = q.shape
    bq = min(block_q, S)
    bk = min(block_k, S)
    scale = float(1.0 / (D ** 0.5))

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2], S, D)

    o3 = _flash(to3(q), to3(k), to3(v), causal, scale, (bq, bk), interpret)
    return o3.reshape(B, H, S, D).transpose(0, 2, 1, 3)
