"""Pallas flash-attention kernel for TPU (placeholder gate this milestone).

The real kernel (online-softmax tiling over KV blocks, VMEM-resident
accumulators — pallas_guide.md patterns) lands in the kernels milestone;
until then ``supported()`` reports False and the XLA einsum path serves all
callers. Model code never imports this module directly — it goes through
ops.attention.dot_product_attention.
"""

from __future__ import annotations

import jax

_ENABLED = False  # flipped when the Pallas kernel lands


def supported(q, k, v, *, causal: bool, mask) -> bool:
    if not _ENABLED:
        return False
    if mask is not None:
        return False
    if q.shape[2] != k.shape[2]:  # GQA handled by pre-repeat in caller for now
        return False
    D = q.shape[-1]
    return D in (64, 128, 256)


def profitable(q) -> bool:
    # Flash pays off once the score matrix stops fitting comfortably in VMEM.
    return q.shape[1] >= 1024


def flash_attention(q, k, v, *, causal: bool = False) -> jax.Array:
    raise NotImplementedError("pallas flash attention not yet enabled")
