"""Pallas TPU flash attention: fused online-softmax attention, fwd + bwd.

The TPU counterpart of the reference stack's fused attention kernels
(torch SDPA/cuDNN flash path — SURVEY C23): never materialises the (S, S)
score matrix in HBM. Forward keeps per-row running max/sum accumulators in
VMEM and streams KV blocks through the MXU (the flash-attention-2
formulation); backward recomputes P per block from the saved logsumexp and
accumulates dQ / dK / dV in two kernels.

Layout: inputs (B, S, H, D) are reshaped to (B·H, S, D); the kernel grid is
(B·H, S/block_q) with an inner arbitrary-order sweep over S/block_k. D must
be 64/128/256 (lane-aligned); S must divide by the block sizes. Softmax math
is fp32 regardless of input dtype (matches ops.attention policy).

GQA is native (r4): K/V stay at Hkv heads in HBM; the batch-major head
order makes q row b's KV row exactly b // rep (rep = H/Hkv), so sharing is
a BlockSpec index_map, not a materialised repeat — K/V read bandwidth drops
by rep. The dK/dV backward adds a rep grid axis that revisits each KV tile
once per query head in its group (first visit zeroes the accumulators,
last writes out).

Causal masking skips whole KV blocks above the diagonal (no wasted MXU work)
and applies an iota mask only on diagonal blocks. Sliding-window attention
(``window > 0``) additionally skips KV blocks entirely below the band, so
compute scales O(S·window) like the chunked XLA path.

Two entry points:
- :func:`flash_attention` — full self-attention, positions implied by the
  block grid (the single-device training path).
- :func:`flash_attention_chunk` — one Q block against one K/V chunk with
  EXPLICIT global position vectors, returning chunk-normalized output plus
  the logsumexp. This is the ring-attention inner kernel (SURVEY §5.7):
  the ring rotates K/V chunks (and their position vectors) around the
  'context' axis and merges chunk results with the flash rule, so the mask
  depends on traced positions, not grid indices. Its custom VJP folds the
  incoming lse cotangent into the flash2 ``delta`` term
  (ds = p∘(dp − (delta − dlse))), so the same backward kernels serve both
  entry points.

Enable/disable: dispatched from ops.attention.dot_product_attention; tests
run interpret=True on CPU against the XLA reference implementation
(SURVEY §5.2 "Pallas kernels → interpret=True mode vs XLA reference").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept both so the
# kernels compile against either pinned jax (utils/compat.py rationale).
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = -1e30

# Tuned on TPU v5e (S=2048, D=128, bf16): large tiles amortize per-program
# overhead — 128x128 ran ~3.5x slower than 512x1024. VMEM check: the f32
# score tile is block_q x block_k x 4B = 2 MB, well inside the ~16 MB budget
# with q/k/v/acc blocks.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def supported(q, k, v, *, causal: bool, mask, window: int = 0) -> bool:
    # window composes with any supported shape (masking + band block skip);
    # it is accepted for API symmetry with the other backends.
    del window
    if mask is not None:
        return False
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if Sq != Sk:  # self-attention only (no KV-cache decode shapes)
        return False
    if D not in (64, 128, 256):
        return False
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H and (Hkv == 0 or H % Hkv != 0):
        return False  # invalid GQA ratio — let the XLA path raise clearly
    bq = min(DEFAULT_BLOCK_Q, Sq)
    bk = min(DEFAULT_BLOCK_K, Sk)
    return Sq % bq == 0 and Sk % bk == 0 and bq % 8 == 0 and bk % 128 == 0


def chunk_supported(q, k, v) -> bool:
    """Shape gate for :func:`flash_attention_chunk` (ring inner kernel):
    GQA-or-MHA heads (Hkv divides H — native in-kernel sharing, r4),
    lane-aligned D, block-divisible LOCAL seq lens (Sq is the device's Q
    shard, Sk the rotating chunk — they may differ)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    Hkv = k.shape[2]
    if k.shape != v.shape:
        return False
    if Hkv != H and (Hkv == 0 or H % Hkv != 0):
        return False
    if D not in (64, 128, 256):
        return False
    bq = min(DEFAULT_BLOCK_Q, Sq)
    bk = min(DEFAULT_BLOCK_K, Sk)
    return Sq % bq == 0 and Sk % bk == 0 and bq % 8 == 0 and bk % 128 == 0


# ------------------------------------------------------------- mask helpers
#
# Shared by all kernels. Positions: iota-from-grid for the full-seq entry,
# explicit (S, 1) i32 refs for the ring-chunk entry (traced, device-local).

def _block_keep(q_start, k_start, qpos_ref, kpos_ref, block_q, block_k,
                causal, window):
    """(block_q, block_k) keep-mask, or None when nothing masks."""
    if not causal and not window:
        return None
    if qpos_ref is not None:
        rows = qpos_ref[...].astype(jnp.int32)  # (block_q, 1)
        cols = kpos_ref[...].astype(jnp.int32).reshape(1, block_k)
    else:
        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
    keep = rows >= cols if causal else None
    if window:
        band = (rows - cols) < window
        keep = band if keep is None else jnp.logical_and(keep, band)
    return keep


def _block_needed(q_start, k_start, qpos_ref, kpos_ref, block_q, block_k,
                  causal, window):
    """Scalar predicate: does this (Q block, KV block) pair intersect the
    causal triangle ∩ window band at all? None → always needed."""
    if not causal and not window:
        return None
    if qpos_ref is not None:
        qp = qpos_ref[...]
        kp = kpos_ref[...]
        q_min, q_max = jnp.min(qp), jnp.max(qp)
        k_min, k_max = jnp.min(kp), jnp.max(kp)
    else:
        q_min, q_max = q_start, q_start + block_q - 1
        k_min, k_max = k_start, k_start + block_k - 1
    needed = q_max >= k_min if causal else None
    if window:
        in_band = k_max > q_min - window
        needed = in_band if needed is None else jnp.logical_and(needed,
                                                                in_band)
    return needed


def profitable(q) -> bool:
    # Below ~1k tokens XLA's fused attention is already fine; flash pays off
    # when the score matrix stops fitting in VMEM.
    return q.shape[1] >= 1024


# ================================================================= forward

def _fwd_kernel(*refs, block_q, block_k, causal, scale, window, has_pos):
    """Grid (BH, nq, nk): one (block_q, D) output tile, sweeping KV blocks."""
    if has_pos:
        (q_ref, k_ref, v_ref, qpos_ref, kpos_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        qpos_ref = kpos_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32)  # (block_q, D)
        kb = k_ref[0].astype(jnp.float32)  # (block_k, D)
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)

        keep = _block_keep(q_start, k_start, qpos_ref, kpos_ref,
                           block_q, block_k, causal, window)
        if keep is not None:
            s = jnp.where(keep, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Rows with EVERY key masked so far (possible for ring chunks and
        # window bands): m_new == NEG_INF, and exp(s - m_new) would be
        # exp(0)=1 for the masked entries. Subtract 0 instead so p stays 0.
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        alpha = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)  # (block_q, block_k)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    needed = _block_needed(q_start, k_start, qpos_ref, kpos_ref,
                           block_q, block_k, causal, window)
    if needed is None:
        _body()
    else:
        pl.when(needed)(_body)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(l_safe)


def _pos_specs(block_q, block_k):
    """BlockSpecs for the (S, 1) / (Sk, 1) i32 position inputs (shared
    across the BH grid axis)."""
    return [
        pl.BlockSpec((block_q, 1), lambda b, i, j: (i, 0)),
        pl.BlockSpec((block_k, 1), lambda b, i, j: (j, 0)),
    ]


def _fwd(q3, k3, v3, q_pos=None, kv_pos=None, *, causal, scale,
         block_q, block_k, window, interpret, out_dtype=None):
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    # GQA without HBM expansion (ROADMAP kernel follow-up): q3 is flattened
    # batch-major with heads in order, so q row b = (batch·Hkv + kvh)·rep + r
    # and its KV row is simply b // rep — an index_map, not a materialized
    # repeat. rep == 1 is the MHA/pre-expanded case (identity map).
    rep = BH // k3.shape[0]
    nq, nk = Sq // block_q, Sk // block_k
    grid = (BH, nq, nk)
    has_pos = q_pos is not None
    out_shape = [
        jax.ShapeDtypeStruct(q3.shape, out_dtype or q3.dtype),  # O
        jax.ShapeDtypeStruct((BH, Sq, 1), jnp.float32),  # LSE (trailing 1: TPU block-shape alignment)
    ]
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k,
        causal=causal, scale=scale, window=window, has_pos=has_pos,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // rep, j, 0)),
    ]
    args = [q3, k3, v3]
    if has_pos:
        in_specs += _pos_specs(block_q, block_k)
        args += [q_pos, kv_pos]
    return pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)


# ================================================================ backward
#
# flash2 backward: with P = exp(S - lse) and delta_i = rowsum(dO_i * O_i):
#   dV_j = sum_i P_ij^T dO_i
#   dP_ij = dO_i V_j^T
#   dS_ij = P_ij * (dP_ij - delta_i)
#   dQ_i = scale * sum_j dS_ij K_j
#   dK_j = scale * sum_i dS_ij^T Q_i

def _bwd_dq_kernel(*refs, block_q, block_k, causal, scale, window, has_pos):
    if has_pos:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qpos_ref, kpos_ref, dq_ref, acc_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, acc_ref) = refs
        qpos_ref = kpos_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # (block_q, 1)
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        keep = _block_keep(q_start, k_start, qpos_ref, kpos_ref,
                           block_q, block_k, causal, window)
        if keep is not None:
            s = jnp.where(keep, s, NEG_INF)
        # Fully-masked rows carry lse == NEG_INF; exp(s - lse) would be
        # exp(0)=1 there — subtract 0 instead so p stays 0.
        lse_safe = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
        p = jnp.exp(s - lse_safe)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    needed = _block_needed(q_start, k_start, qpos_ref, kpos_ref,
                           block_q, block_k, causal, window)
    if needed is None:
        _body()
    else:
        pl.when(needed)(_body)

    @pl.when(ki == nk - 1)
    def _fin():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, block_q, block_k, causal, scale, window, has_pos):
    """Grid (B·Hkv, nk, rep, nq): one (block_k, D) dK/dV tile. The rep axis
    revisits the SAME KV tile for each of the rep query heads sharing it
    (GQA) — first visit (r==0, qi==0) zeroes the accumulators, every visit
    adds, the last (r==rep-1, qi==nq-1) writes out. rep==1 is MHA."""
    if has_pos:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qpos_ref, kpos_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qpos_ref = kpos_ref = None
    ki = pl.program_id(1)
    r = pl.program_id(2)
    qi = pl.program_id(3)
    rep = pl.num_programs(2)
    nq = pl.num_programs(3)

    @pl.when((qi == 0) & (r == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # (block_q, 1)
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        keep = _block_keep(q_start, k_start, qpos_ref, kpos_ref,
                           block_q, block_k, causal, window)
        if keep is not None:
            s = jnp.where(keep, s, NEG_INF)
        lse_safe = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
        p = jnp.exp(s - lse_safe)  # (block_q, block_k)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_k, D)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_k, D)

    needed = _block_needed(q_start, k_start, qpos_ref, kpos_ref,
                           block_q, block_k, causal, window)
    if needed is None:
        _body()
    else:
        pl.when(needed)(_body)

    @pl.when((qi == nq - 1) & (r == rep - 1))
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, lse, do3, q_pos=None, kv_pos=None, *, causal,
         scale, block_q, block_k, window, interpret, dlse=None):
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    rep = BH // k3.shape[0]  # GQA group size (see _fwd); 1 = MHA
    nq, nk = Sq // block_q, Sk // block_k
    has_pos = q_pos is not None
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[..., None]
    if dlse is not None:
        # Chunk entry: the lse output has its own cotangent. With
        # lse = logsumexp(s), d lse/d s = p, so ds gains +p·dlse — which
        # folds into the flash2 formula as delta' = delta − dlse.
        delta = delta - dlse

    dq_in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_args = [q3, k3, v3, do3, lse, delta]
    if has_pos:
        dq_in_specs += _pos_specs(block_q, block_k)
        dq_args += [q_pos, kv_pos]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale, window=window,
                          has_pos=has_pos),
        grid=(BH, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dq_args)

    # dK/dV grid (B·Hkv, nk, rep, nq): q-side rows for KV row b are
    # b·rep + r — the inverse of the forward's b // rep map.
    dkv_in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, j, r, i: (b * rep + r, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, j, r, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, j, r, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, D), lambda b, j, r, i: (b * rep + r, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, r, i: (b * rep + r, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, r, i: (b * rep + r, i, 0)),
    ]
    dkv_args = [q3, k3, v3, do3, lse, delta]
    if has_pos:
        dkv_in_specs += [
            pl.BlockSpec((block_q, 1), lambda b, j, r, i: (i, 0)),
            pl.BlockSpec((block_k, 1), lambda b, j, r, i: (j, 0)),
        ]
        dkv_args += [q_pos, kv_pos]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale, window=window,
                          has_pos=has_pos),
        grid=(BH // rep, nk, rep, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, r, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, r, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


# ============================================================== public API

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q3, k3, v3, causal, scale, block_sizes, interpret, window):
    o, _ = _fwd(q3, k3, v3, causal=causal, scale=scale,
                block_q=block_sizes[0], block_k=block_sizes[1],
                window=window, interpret=interpret)
    return o


def _flash_fwd(q3, k3, v3, causal, scale, block_sizes, interpret, window):
    o, lse = _fwd(q3, k3, v3, causal=causal, scale=scale,
                  block_q=block_sizes[0], block_k=block_sizes[1],
                  window=window, interpret=interpret)
    return o, (q3, k3, v3, o, lse)


def _flash_bwd(causal, scale, block_sizes, interpret, window, res, do3):
    q3, k3, v3, o3, lse = res
    dq, dk, dv = _bwd(q3, k3, v3, o3, lse, do3, causal=causal, scale=scale,
                      block_q=block_sizes[0], block_k=block_sizes[1],
                      window=window, interpret=interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    window: int = 0,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """(B, S, H, D) attention via the Pallas kernel. GQA (Hkv < H,
    H % Hkv == 0) is NATIVE: K/V stay at Hkv heads in HBM and the kernel's
    BlockSpec index_map (q row b → KV row b // rep) shares each KV tile
    across its query group — no expanded copy is ever materialised
    (forward reads H/Hkv x less K/V bandwidth than an expand-first
    design). ``window`` > 0 restricts each query to its trailing
    ``window`` keys (requires causal — enforced upstream)."""
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H and (Hkv == 0 or H % Hkv != 0):
        raise ValueError(
            f"invalid GQA ratio: {H} query heads over {Hkv} KV heads")
    bq = min(block_q, S)
    bk = min(block_k, S)
    scale = float(1.0 / (D ** 0.5))

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2], S, D)

    o3 = _flash(to3(q), to3(k), to3(v), causal, scale, (bq, bk), interpret,
                int(window))
    return o3.reshape(B, H, S, D).transpose(0, 2, 1, 3)


# ----------------------------------------------------- ring-chunk entry

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_chunk(q3, k3, v3, qp, kp, causal, scale, block_sizes, interpret,
                 window):
    o, lse = _fwd(q3, k3, v3, qp, kp, causal=causal, scale=scale,
                  block_q=block_sizes[0], block_k=block_sizes[1],
                  window=window, interpret=interpret, out_dtype=jnp.float32)
    return o, lse


def _flash_chunk_fwd(q3, k3, v3, qp, kp, causal, scale, block_sizes,
                     interpret, window):
    o, lse = _flash_chunk(q3, k3, v3, qp, kp, causal, scale, block_sizes,
                          interpret, window)
    return (o, lse), (q3, k3, v3, qp, kp, o, lse)


def _flash_chunk_bwd(causal, scale, block_sizes, interpret, window, res, ct):
    q3, k3, v3, qp, kp, o3, lse = res
    do3, dlse = ct
    dq, dk, dv = _bwd(q3, k3, v3, o3, lse, do3.astype(jnp.float32), qp, kp,
                      causal=causal, scale=scale,
                      block_q=block_sizes[0], block_k=block_sizes[1],
                      window=window, interpret=interpret, dlse=dlse)
    zero = lambda x: np.zeros(x.shape, jax.dtypes.float0)  # noqa: E731
    return dq, dk, dv, zero(qp), zero(kp)


_flash_chunk.defvjp(_flash_chunk_fwd, _flash_chunk_bwd)


def flash_attention_chunk(q, k, v, q_pos, kv_pos, *, causal: bool,
                          window: int = 0,
                          block_q: int = DEFAULT_BLOCK_Q,
                          block_k: int = DEFAULT_BLOCK_K,
                          interpret: bool = False):
    """One Q shard against ONE K/V chunk with explicit global positions —
    the ring-attention inner step (ops/ring_attention.py).

    q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D) — GQA taken UNEXPANDED (the
    in-kernel b // rep sharing, r4); q_pos: (Sq,) i32;
    kv_pos: (Sk,) i32 (traced — they rotate with the chunk).
    Returns (o, lse): o (B, Sq, H, D) fp32 normalized WITHIN the chunk,
    lse (B, H, Sq) fp32, NEG_INF on fully-masked rows — the contract
    ring_attention's merge rule expects. Differentiable in q/k/v including
    through lse (the merge weights), via the folded-delta custom VJP.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    bq = min(block_q, Sq)
    bk = min(block_k, Sk)
    scale = float(1.0 / (D ** 0.5))
    qp = q_pos.astype(jnp.int32).reshape(Sq, 1)
    kp = kv_pos.astype(jnp.int32).reshape(Sk, 1)

    def to3(x):  # per-tensor head count: k/v stay at Hkv rows (GQA)
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2],
                                               x.shape[1], D)

    o3, lse = _flash_chunk(to3(q), to3(k), to3(v), qp, kp, causal, scale,
                           (bq, bk), interpret, int(window))
    o = o3.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    return o, lse.reshape(B, H, Sq)
