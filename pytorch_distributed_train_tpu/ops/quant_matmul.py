"""Fused weight-dequant matmul Pallas kernels (W8A16 / W4A16 GEMV).

Why this kernel exists (AOT_AB.json, round 5): XLA materializes the
dequantized bf16 weights of the weight-only int8/int4 decode path —
the quantized array is what LIVES in HBM between steps, but each step
still writes + re-reads a full bf16 copy (the v5e cost model shows
int4 decode accessing 2.9x int8's bytes, with ~288 MiB of dequant
temps per step). That forfeits exactly the bandwidth the quantization
was meant to save in the HBM-bound decode regime.

This kernel performs the dequant IN VMEM, between the HBM read and the
MXU: each grid step streams one (H, TILE_N) int8/int4 weight tile and
its scales into VMEM, converts in-register, and dots against the
(rows, H) activations — HBM traffic is the QUANTIZED bytes plus the
small activations/outputs, never a bf16 weight copy. TILE_N aligns to
the int4 GROUP (128), so a tile sees exactly one scale column per
input row (int4) or one scale row (int8's per-output channels).

Decode shapes: x is (rows, H) with rows = B*S tiny (1..k+1 per
sequence in a serving batch), W is (H, N). The contraction dim H stays
UNTILED (a 4096 x 128 int4 tile is 256 KiB — comfortably VMEM); rows
pad to the fp32 sublane tile (8).

Scale layouts (quant.py):
- int8 ``quantize_leaf``: per-output-channel, scale (1, N).
- int4 ``quantize_leaf_int4``: per (input row, output group of G),
  scale (H, N/G, 1) — the scale sits INSIDE the contraction, which is
  why it cannot be factored out of the matmul after the fact.

Validated like the flash kernels: interpret-mode numerics on CPU
(tests/test_quant_matmul.py) + deviceless v5e Mosaic AOT compile
(tools/mosaic_aot_battery.py). Integration into the decode model path
is the documented follow-up — the kernel is the hard part the cost
model demanded.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_N = 128  # == quant.py's int4 group size; one scale column per tile


def _w8_kernel(x_ref, w_ref, s_ref, o_ref, *, out_dtype):
    # x: (R, H) bf16; w: (H, T) int8; s: (1, T) f32 per-output scales
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(out_dtype)


def _w4_kernel(x_ref, w_ref, s_ref, o_ref, *, out_dtype):
    # x: (R, H) bf16; w: (H, T) int4; s: (NG, H) f32 — the FULL scale
    # table, transposed. Scale varies along the CONTRACTION dim, so it
    # must multiply the weights BEFORE the dot — in VMEM, not in HBM.
    # The whole (NG, H) table rides one constant-index block (Mosaic
    # tiling forbids an (H, 1) column block; the pipeline keeps a
    # constant block resident across grid steps, so HBM reads it once)
    # and the tile's group column is a dynamic row slice at grid index
    # j — tile width == group size makes j THE group id.
    x = x_ref[...].astype(jnp.float32)
    # row select without dynamic_slice (unimplemented in the TC
    # lowering): mask-reduce the table against an iota — 43-row
    # VMEM math, negligible next to the dot
    s = s_ref[...]  # (NG, H)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    col = jnp.sum(jnp.where(rows == pl.program_id(0), s, 0.0),
                  axis=0, keepdims=True)  # (1, H)
    w = w_ref[...].astype(jnp.float32) * col.T  # (H, T) * (H, 1)
    o_ref[...] = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(out_dtype)


def _pad_rows(x2, mult: int = 8):
    R = x2.shape[0]
    pad = (-R) % mult
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, R


def quant_matmul(x: jax.Array, q: dict, *, interpret: bool = False,
                 out_dtype=None) -> jax.Array:
    """``x @ dequant(q)`` with the dequant fused into the tile stream.

    x: (..., H) activations (bf16/f32); q: a quant.py struct —
    {'w_int8', 'scale'} (per-output scales) or {'w_int4', 'scale'}
    (group-wise). Returns (..., N) in ``out_dtype`` (default x.dtype).
    N and (for int4) H must be multiples of TILE_N and the group size
    respectively — true for every transformer kernel this serves.
    """
    from pytorch_distributed_train_tpu import quant

    if not quant._is_quant_leaf(q):
        raise ValueError(
            "quant_matmul takes a quant.py leaf struct "
            f"({{'w_int8'|'w_int4', 'scale'}}), got keys "
            f"{sorted(q) if isinstance(q, dict) else type(q).__name__}")
    out_dtype = out_dtype or x.dtype
    lead = x.shape[:-1]
    H = x.shape[-1]
    x2, R = _pad_rows(x.reshape(-1, H))
    Rp = x2.shape[0]

    if quant._W4 in q:
        w, scale = q[quant._W4], q[quant._S]
        axis, G = quant._int4_grouping(w.shape, scale.shape)
        N = w.shape[1]
        if (w.ndim != 2 or w.shape[0] != H or axis != 1 or G != TILE_N
                or N % TILE_N):
            raise ValueError(
                f"W4 fused matmul needs a 2D (H={H}, N) weight grouped "
                f"along axis 1 with G == {TILE_N} and N % {TILE_N} == "
                f"0, got shape {w.shape}, axis {axis}, G {G}")
        s2t = scale.reshape(H, N // G).T  # (NG, H): row g scales tile g
        out = pl.pallas_call(
            functools.partial(_w4_kernel, out_dtype=out_dtype),
            grid=(N // TILE_N,),
            in_specs=[
                pl.BlockSpec((Rp, H), lambda j: (0, 0)),
                pl.BlockSpec((H, TILE_N), lambda j: (0, j)),
                pl.BlockSpec((N // G, H), lambda j: (0, 0)),
            ],
            out_specs=pl.BlockSpec((Rp, TILE_N), lambda j: (0, j)),
            out_shape=jax.ShapeDtypeStruct((Rp, N), out_dtype),
            interpret=interpret,
        )(x2, w, s2t)
    else:
        w, scale = q[quant._W], q[quant._S]
        if (w.ndim != 2 or w.shape[0] != H or w.shape[1] % TILE_N
                or scale.shape != (1, w.shape[1])):
            raise ValueError(
                f"W8 fused matmul needs a 2D (H={H}, N) weight with "
                f"per-output (1, N) scales and N % {TILE_N} == 0, got "
                f"w {w.shape}, scale {scale.shape}")
        N = w.shape[1]
        out = pl.pallas_call(
            functools.partial(_w8_kernel, out_dtype=out_dtype),
            grid=(N // TILE_N,),
            in_specs=[
                pl.BlockSpec((Rp, H), lambda j: (0, 0)),
                pl.BlockSpec((H, TILE_N), lambda j: (0, j)),
                pl.BlockSpec((1, TILE_N), lambda j: (0, j)),
            ],
            out_specs=pl.BlockSpec((Rp, TILE_N), lambda j: (0, j)),
            out_shape=jax.ShapeDtypeStruct((Rp, N), out_dtype),
            interpret=interpret,
        )(x2, w, scale.astype(jnp.float32))
    return out[:R].reshape(*lead, N)
