"""In-graph model-health statistics: the training-dynamics telemetry
pass (ISSUE 20 tentpole, obs/model_health.py's device-side half).

One traversal of the gradient/param/update trees per step computes, for
every TOP-LEVEL module of the parameter tree, the gradient norm, the
parameter norm, the update norm and the update-to-param ratio — the
classic divergence precursors (per-block gradient explosion, an update
that suddenly dwarfs the weights it lands on) — plus the tree-wide
aggregates the fleet alert rules watch. Everything is reduced IN-GRAPH
to scalars, so the host cost stays one transfer at log cadence no
matter how many modules the model has.

The update norm is measured on the ACTUAL applied update
(``new_params - params``), not the optimizer's proposed update: the
numeric-guard skip branch, loss-scale gating and the LR-cooldown leaf
are all reflected for free (a skipped step reads as update_norm 0).

The jitted-step purity contract applies (tools/analyze jit-purity pass
covers this file): everything here is traced math — no host syncs, no
prints, no wall clocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Floor under the param norm in the update-to-param ratio: a freshly
# zero-initialized block (biases, layernorm offsets) must read as
# "huge update" via the numerator, not divide by zero.
_RATIO_EPS = 1e-12


def _sumsq(tree) -> jnp.ndarray:
    return sum(
        (jnp.sum(jnp.square(x.astype(jnp.float32)))
         for x in jax.tree_util.tree_leaves(tree)),
        start=jnp.float32(0.0))


def _diff_sumsq(new_tree, old_tree) -> jnp.ndarray:
    return sum(
        (jnp.sum(jnp.square(n.astype(jnp.float32)
                            - o.astype(jnp.float32)))
         for n, o in zip(jax.tree_util.tree_leaves(new_tree),
                         jax.tree_util.tree_leaves(old_tree))),
        start=jnp.float32(0.0))


def health_stats(grads, params, new_params) -> dict:
    """Per-top-level-module training-dynamics stats + aggregates.

    Returns a flat metrics dict of f32 scalars:

    - ``grad_norm/<module>``, ``param_norm/<module>``,
      ``update_norm/<module>``, ``update_ratio/<module>`` for every
      top-level key of the param tree (the ``module=`` label series on
      the scrape surface — obs/registry.set_from_mapping);
    - ``param_norm``, ``update_norm`` — tree-wide norms (``grad_norm``
      is already in the step metrics);
    - ``update_ratio_max`` — the worst module's update-to-param ratio,
      the scalar the ``grad_norm_spike`` early-warning path pairs with.

    Caller contract (steps.py): only ever ADDS metrics entries — the
    update path itself is untouched, so ``obs.model_health`` off is
    bitwise identical to the pre-telemetry step.
    """
    stats: dict[str, jnp.ndarray] = {}
    param_sq = jnp.float32(0.0)
    update_sq = jnp.float32(0.0)
    ratios = []
    for key in grads:
        g_sq = _sumsq(grads[key])
        p_sq = _sumsq(params[key])
        u_sq = _diff_sumsq(new_params[key], params[key])
        p_norm = jnp.sqrt(p_sq)
        u_norm = jnp.sqrt(u_sq)
        ratio = u_norm / (p_norm + _RATIO_EPS)
        stats[f"grad_norm/{key}"] = jnp.sqrt(g_sq)
        stats[f"param_norm/{key}"] = p_norm
        stats[f"update_norm/{key}"] = u_norm
        stats[f"update_ratio/{key}"] = ratio
        param_sq = param_sq + p_sq
        update_sq = update_sq + u_sq
        ratios.append(ratio)
    stats["param_norm"] = jnp.sqrt(param_sq)
    stats["update_norm"] = jnp.sqrt(update_sq)
    stats["update_ratio_max"] = (
        jnp.max(jnp.stack(ratios)) if ratios else jnp.float32(0.0))
    return stats
