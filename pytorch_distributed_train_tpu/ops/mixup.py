"""Device-side MixUp / CutMix batch augmentation.

The reference-era torchvision/timm recipes apply MixUp (Zhang et al. 2018)
and CutMix (Yun et al. 2019) as a host-side collate transform
(torchvision.transforms.v2.{MixUp,CutMix}, timm.data.Mixup). On TPU the
idiomatic place is INSIDE the jitted train step: the mix is a handful of
elementwise ops on a batch already resident in HBM, it fuses into the
forward, and the host pipeline stays on the fast path. Shapes stay static
(box masks are arange comparisons, never dynamic slices), so there is no
recompilation hazard.

Semantics (matching timm.data.Mixup defaults, batch-wise mode):
- per batch, draw lam ~ Beta(alpha, alpha); partner sample = the adjacent
  element (pairwise swap 0↔1, 2↔3, …). timm pairs with the reversed batch
  (``x.flip(0)``) — statistically equivalent, but a reverse along a
  batch axis sharded over the 'data' mesh axis lowers to a collective
  permute of the WHOLE image tensor every step; the pairwise swap is a
  reshape + reverse of an unsharded length-2 axis, which stays shard-local
  whenever the per-shard batch is even (falls back to the reverse for odd
  batches);
- if both mixup_alpha and cutmix_alpha are enabled, a Bernoulli(switch_prob)
  draw picks CutMix vs MixUp for the whole batch;
- CutMix cuts a box of area ratio (1 - lam) with uniformly-random center,
  clipped to the image, then sets lam := 1 - cut_area/total_area (the
  correction for clipping);
- targets become the convex combination of the one-hot (optionally
  label-smoothed) target rows: lam * y + (1 - lam) * y_flipped, shipped to
  the loss as ``batch['target_probs']`` (soft-target cross-entropy).

``batch['label']`` is kept (unmixed) so accuracy metrics stay comparable
with un-augmented runs.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import optax


def _sample_beta(rng: jax.Array, alpha: float) -> jnp.ndarray:
    """One Beta(alpha, alpha) draw via two Gammas (jax.random.beta)."""
    return jax.random.beta(rng, alpha, alpha)


def partner(x: jnp.ndarray) -> jnp.ndarray:
    """Mix partner along the batch axis: pairwise swap [1,0,3,2,…].

    Shard-local under 'data'-axis batch sharding (see module docstring);
    odd batch sizes fall back to the full reverse.
    """
    batch = x.shape[0]
    if batch % 2:
        return x[::-1]
    paired = x.reshape((batch // 2, 2) + x.shape[1:])
    return paired[:, ::-1].reshape(x.shape)


def _cutmix_box_mask(rng: jax.Array, height: int, width: int,
                     lam: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(H, W) bool mask that is True INSIDE the cut box, plus corrected lam.

    Box edge ratio sqrt(1-lam) per CutMix; center uniform over the image;
    the box is clipped at the borders, so the realized area can be smaller
    than requested — lam is recomputed from the realized area exactly as
    timm's ``cutmix_bbox_and_lam(correct_lam=True)`` does.
    """
    ratio = jnp.sqrt(1.0 - lam)
    cut_h = (height * ratio).astype(jnp.int32)
    cut_w = (width * ratio).astype(jnp.int32)
    rng_y, rng_x = jax.random.split(rng)
    cy = jax.random.randint(rng_y, (), 0, height)
    cx = jax.random.randint(rng_x, (), 0, width)
    y0 = jnp.clip(cy - cut_h // 2, 0, height)
    y1 = jnp.clip(cy + cut_h // 2, 0, height)
    x0 = jnp.clip(cx - cut_w // 2, 0, width)
    x1 = jnp.clip(cx + cut_w // 2, 0, width)
    rows = jnp.arange(height)[:, None]
    cols = jnp.arange(width)[None, :]
    mask = (rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1)
    area = ((y1 - y0) * (x1 - x0)).astype(jnp.float32)
    lam_corrected = 1.0 - area / float(height * width)
    return mask, lam_corrected


@dataclass(frozen=True)
class MixupCutmix:
    """Batch transform: (batch, rng) -> batch with mixed images + soft targets.

    All fields are static (closed over by the jitted step). Disabled axes
    (alpha == 0) are never traced in.
    """

    mixup_alpha: float = 0.0
    cutmix_alpha: float = 0.0
    switch_prob: float = 0.5  # P(cutmix) when both enabled
    num_classes: int = 0
    label_smoothing: float = 0.0

    @property
    def enabled(self) -> bool:
        return self.mixup_alpha > 0.0 or self.cutmix_alpha > 0.0

    def __call__(self, batch: dict, rng: jax.Array) -> dict:
        if not self.enabled:
            return batch
        if self.num_classes <= 0:
            raise ValueError("MixupCutmix needs num_classes > 0")
        images = batch["image"]
        labels = batch["label"]
        height, width = images.shape[1], images.shape[2]

        rng_lam, rng_box, rng_switch = jax.random.split(rng, 3)
        flipped = partner(images)

        def mixup_branch():
            lam = _sample_beta(rng_lam, self.mixup_alpha)
            mixed = lam * images + (1.0 - lam) * flipped
            return mixed.astype(images.dtype), lam

        def cutmix_branch():
            lam0 = _sample_beta(rng_lam, self.cutmix_alpha)
            mask, lam = _cutmix_box_mask(rng_box, height, width, lam0)
            mixed = jnp.where(mask[None, :, :, None], flipped, images)
            return mixed, lam

        if self.mixup_alpha > 0.0 and self.cutmix_alpha > 0.0:
            use_cutmix = jax.random.bernoulli(rng_switch, self.switch_prob)
            mixed, lam = jax.lax.cond(
                use_cutmix, cutmix_branch, mixup_branch)
        elif self.cutmix_alpha > 0.0:
            mixed, lam = cutmix_branch()
        else:
            mixed, lam = mixup_branch()

        one_hot = jax.nn.one_hot(labels, self.num_classes)
        if self.label_smoothing > 0.0:
            one_hot = optax.smooth_labels(one_hot, self.label_smoothing)
        targets = lam * one_hot + (1.0 - lam) * partner(one_hot)

        out = dict(batch)
        out["image"] = mixed
        out["target_probs"] = targets
        return out


def build_mixup(data_cfg, model_cfg, label_smoothing: float,
                loss: str = "softmax_xent") -> MixupCutmix | None:
    """Config → transform (or None when disabled). Mirrors the torchvision
    recipe flags --mixup-alpha/--cutmix-alpha. Validates workload
    compatibility at construction time (a config error here would otherwise
    surface as an opaque KeyError deep inside the jit trace)."""
    m = MixupCutmix(
        mixup_alpha=data_cfg.mixup_alpha,
        cutmix_alpha=data_cfg.cutmix_alpha,
        switch_prob=data_cfg.mixup_switch_prob,
        num_classes=model_cfg.num_classes,
        label_smoothing=label_smoothing,
    )
    if not m.enabled:
        return None
    if loss != "softmax_xent":
        raise ValueError(
            f"mixup/cutmix requires an image-classification workload "
            f"(loss='softmax_xent'); this config uses loss={loss!r}")
    return m
