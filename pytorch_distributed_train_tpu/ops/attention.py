"""Attention core with a single dispatch point.

All transformer models route through :func:`dot_product_attention`, so the
implementation (XLA einsum path vs Pallas flash kernel) is swappable without
touching model code — the analogue of torch's `scaled_dot_product_attention`
backend dispatch, but resolved statically.

Shapes follow the TPU-friendly convention (batch, seq, heads, head_dim) —
"BSHD" — which keeps the head dim last (lane dim, 128-multiple for the MXU)
and avoids the NCHW-style transposes torch attention does.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dot_product_attention(
    q: jax.Array,  # (B, Sq, H, D)
    k: jax.Array,  # (B, Sk, H_kv, D)
    v: jax.Array,  # (B, Sk, H_kv, D)
    *,
    causal: bool = False,
    mask: jax.Array | None = None,  # (B, 1, Sq, Sk) or broadcastable, True=keep
    softmax_dtype: jnp.dtype = jnp.float32,
    impl: str = "auto",  # auto | xla | pallas
) -> jax.Array:
    """Multi-head attention core, GQA-aware.

    Softmax is always computed in fp32 (``softmax_dtype``) regardless of the
    bf16 compute policy — the TPU replacement for autocast's per-op allowlist
    keeping softmax in fp32 (SURVEY C18).
    """
    if impl in ("auto", "pallas"):
        from pytorch_distributed_train_tpu.ops import flash_attention as _fa

        on_tpu = _on_tpu()
        if _fa.supported(q, k, v, causal=causal, mask=mask):
            # impl='pallas' forces the kernel anywhere (interpret mode off-TPU
            # — slow but exact, which is what tests and debugging want);
            # 'auto' uses it only on TPU where it pays off.
            if impl == "pallas" or (on_tpu and _fa.profitable(q)):
                H, Hkv = q.shape[2], k.shape[2]
                if Hkv != H:  # GQA: expand KV for the kernel
                    # TODO(perf): index kv blocks as b // rep in the kernel
                    # instead of materialising the repeat in HBM.
                    k = jnp.repeat(k, H // Hkv, axis=2)
                    v = jnp.repeat(v, H // Hkv, axis=2)
                return _fa.flash_attention(q, k, v, causal=causal,
                                           interpret=not on_tpu)
        elif impl == "pallas":
            raise ValueError("pallas flash attention unsupported for these shapes")
    return _xla_attention(q, k, v, causal=causal, mask=mask, softmax_dtype=softmax_dtype)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _xla_attention(q, k, v, *, causal, mask, softmax_dtype):
    orig_dtype = q.dtype
    B, Sq, H, D = q.shape
    _, Sk, Hkv, _ = k.shape
    if Hkv != H:
        # GQA: repeat KV heads up to H (XLA fuses the broadcast into the matmul)
        if H % Hkv != 0:
            raise ValueError(f"heads {H} not divisible by kv heads {Hkv}")
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    scale = 1.0 / jnp.sqrt(D).astype(softmax_dtype)
    # (B, H, Sq, Sk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=softmax_dtype)
    logits = logits * scale

    if causal:
        q_pos = jnp.arange(Sq)[:, None] + (Sk - Sq)  # align ends for KV-cache decode
        k_pos = jnp.arange(Sk)[None, :]
        causal_mask = q_pos >= k_pos
        logits = jnp.where(causal_mask[None, None], logits, _neg_inf(softmax_dtype))
    if mask is not None:
        logits = jnp.where(mask, logits, _neg_inf(softmax_dtype))

    probs = jax.nn.softmax(logits, axis=-1).astype(orig_dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _neg_inf(dtype) -> jax.Array:
    return jnp.asarray(jnp.finfo(dtype).min, dtype)
