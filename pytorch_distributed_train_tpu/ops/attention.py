"""Attention core with a single dispatch point.

All transformer models route through :func:`dot_product_attention`, so the
implementation (XLA einsum path vs Pallas flash kernel) is swappable without
touching model code — the analogue of torch's `scaled_dot_product_attention`
backend dispatch, but resolved statically.

Shapes follow the TPU-friendly convention (batch, seq, heads, head_dim) —
"BSHD" — which keeps the head dim last (lane dim, 128-multiple for the MXU)
and avoids the NCHW-style transposes torch attention does.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp

# Process-wide default for impl="auto" callers. Models thread their own
# ModelConfig.attention_impl as a static module attr, so this global is
# only the operator-level control. Resolution order for an "auto" call:
# PDTT_ATTENTION_IMPL env var > set_default_impl() > "auto" heuristic.
# The torch analogue is the global torch.backends.cuda.sdp_kernel switch.
_default_impl = "auto"

_VALID_IMPLS = ("auto", "xla", "pallas", "chunked")


def set_default_impl(impl: str) -> None:
    if impl not in _VALID_IMPLS:
        raise ValueError(
            f"attention impl must be one of {_VALID_IMPLS}, got {impl!r}")
    global _default_impl
    _default_impl = impl


def _env_impl() -> str | None:
    env = os.environ.get("PDTT_ATTENTION_IMPL")
    if env is not None and env not in _VALID_IMPLS:
        raise ValueError(
            f"PDTT_ATTENTION_IMPL must be one of {_VALID_IMPLS}, got {env!r}"
        )
    return env


def _resolve_default_impl() -> str:
    return _env_impl() or _default_impl


def _mosaic_probe_record(path: str | None = None) -> dict | None:
    """The recorded Mosaic-compile probe (tools/mosaic_probe.py), or None.

    Cached per-path after the first read: _pallas_usable sits on the
    attention dispatch path. MOSAIC_PROBE_PATH overrides for tests."""
    path = path or os.environ.get("MOSAIC_PROBE_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "MOSAIC_PROBE.json")
    rec = _mosaic_probe_cache.get(path)
    if rec is None:
        try:
            import json

            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rec = {}
        _mosaic_probe_cache[path] = rec
    return rec or None


_mosaic_probe_cache: dict[str, dict] = {}


def _pallas_usable() -> bool:
    """Whether impl='auto' may pick the Pallas kernel on this backend.

    The sandbox's tunnelled axon PJRT (JAX_PLATFORMS=axon, remote compile)
    historically cannot compile Mosaic kernels — a tiny flash-attention
    fwd hung >8 min and wedged the device lease. Rather than hardcoding
    that forever, the gate is PROBE-DRIVEN (VERDICT r3 #4): when a
    recorded `tools/mosaic_probe.py` run exists, its measured verdict
    wins — status "ok" opens the kernel even under axon, anything else
    keeps routing around it. With no record, axon backends stay gated by
    the historical default. Explicit impl='pallas' still forces the
    kernel anywhere.
    """
    cfg_platforms = getattr(jax.config, "jax_platforms", None) or ""
    on_axon = ("axon" in os.environ.get("JAX_PLATFORMS", "")
               or "axon" in cfg_platforms)
    if not on_axon:
        return True
    rec = _mosaic_probe_record()
    # The record only overrides when it was CAPTURED against the axon
    # stack — an "ok" measured on a direct TPU says nothing about the
    # tunnel's remote compile and must not re-open the lease-wedge.
    if (rec and rec.get("status")
            and "axon" in rec.get("jax_platforms_env", "")):
        if rec["status"] != "ok":
            return False
        # When the probe also timed the flash-vs-chunked A/B, auto must
        # pick the measured WINNER: an ok-but-slower kernel (v5e probe
        # 2026-08-02: flash 125.7ms vs chunked 17.7ms fwd+bwd) would
        # otherwise silently regress every impl='auto' caller. Explicit
        # impl='pallas' still forces the kernel for tuning work.
        flash, chunked = rec.get("flash_ms"), rec.get("chunked_ms")
        if flash is not None and chunked is not None:
            return float(flash) <= float(chunked)
        return True
    return False


@dataclasses.dataclass(frozen=True)
class ContextParallelConfig:
    """Static recipe for sequence/context parallelism (SURVEY §5.7, §2.3).

    Passed down from the mesh config to attention modules; hashable so flax
    modules can hold it as a static attribute. ``impl``:
      ring    — lax.ppermute KV rotation, scales to any axis size
      ulysses — all-to-all head↔seq swap, needs heads % axis size == 0
    """

    mesh: jax.sharding.Mesh
    impl: str = "ring"  # ring | ulysses
    # Ring sequence layout: "zigzag" pairs chunk i with 2n−1−i per device
    # so causal work balances across the ring (ring_attention.zigzag_perm);
    # ignored by ulysses and by non-causal calls.
    layout: str = "contiguous"  # contiguous | zigzag
    context_axis: str = "context"
    batch_axes: tuple[str, ...] = ("data", "fsdp")
    tensor_axis: str | None = "tensor"

    @property
    def active(self) -> bool:
        return self.mesh.shape[self.context_axis] > 1

    def activation_sharding(self, ndim: int) -> jax.sharding.NamedSharding:
        """(B, S, ...) activation sharding: batch over batch_axes, seq over
        the context axis — the constraint models apply so pre/post-attention
        pointwise compute stays seq-sharded instead of replicating."""
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(tuple(self.batch_axes), self.context_axis,
                             *([None] * (ndim - 2)))
        return NamedSharding(self.mesh, spec)


def dot_product_attention(
    q: jax.Array,  # (B, Sq, H, D)
    k: jax.Array,  # (B, Sk, H_kv, D)
    v: jax.Array,  # (B, Sk, H_kv, D)
    *,
    causal: bool = False,
    mask: jax.Array | None = None,  # (B, 1, Sq, Sk) or broadcastable, True=keep
    softmax_dtype: jnp.dtype = jnp.float32,
    impl: str = "auto",  # auto | xla | pallas | chunked
    cp: ContextParallelConfig | None = None,
    window: int = 0,  # >0: sliding window — attend to the last `window` keys
    segments: jax.Array | None = None,  # (B, S) ids; attend only within ==
) -> jax.Array:
    """Multi-head attention core, GQA-aware.

    Softmax is always computed in fp32 (``softmax_dtype``) regardless of the
    bf16 compute policy — the TPU replacement for autocast's per-op allowlist
    keeping softmax in fp32 (SURVEY C18).

    With an *active* ``cp`` the sequence dim is sharded over the context mesh
    axis and the core routes through ring attention or Ulysses (SURVEY §5.7)
    inside a shard_map region embedded in the surrounding GSPMD program.
    Contract under cp: Ulysses forwards ``impl`` to its local full-sequence
    core; ring attention is its own implementation (``impl`` does not apply)
    and always does fp32 chunk softmax — same as the default
    ``softmax_dtype``, which cp paths do not override.
    """
    if impl not in _VALID_IMPLS:
        raise ValueError(
            f"attention impl must be one of {_VALID_IMPLS}, got {impl!r}")
    if window:
        # Mistral-style sliding window: only defined relative to causal
        # ordering (each query sees its trailing `window` keys). Composes
        # with every backend: xla/chunked mask or band-slice, pallas masks
        # within tiles and skips out-of-band blocks, ring skips whole
        # out-of-band hops, ulysses applies it on the full-seq local core.
        if window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        if not causal:
            raise ValueError("window attention requires causal=True")
    # The env var is the operator's kill switch: it beats EVERYTHING,
    # including an explicit impl arg or a config-threaded backend — its
    # whole purpose is preventing Mosaic-compile hangs no matter what the
    # config says.
    env = _env_impl()
    if env is not None:
        impl = env
    elif impl == "auto":
        impl = _default_impl
    if segments is not None:
        # Packed-document isolation (models pass the (B, S) segment ids,
        # NOT a materialised (B, 1, S, S) mask — the xla path builds it
        # where it materialises S^2 scores anyway, the chunked path
        # builds one (B, 1, chunk, Sk) tile at a time).
        if q.shape[1] != k.shape[1]:
            raise ValueError("segments requires self-attention shapes")
        if impl == "pallas":
            raise ValueError(
                "the pallas flash kernel does not take segment ids "
                "(packed-document isolation) — use impl='xla' or "
                "'chunked' for segment_eos_id runs")
        if cp is not None and cp.active:
            raise NotImplementedError(
                "segments with context parallelism is unsupported")
    if cp is not None and cp.active:
        if cp.impl == "ring":
            if mask is not None:
                raise NotImplementedError(
                    "ring attention supports causal masking only; use "
                    "context_impl='ulysses' for padded/arbitrary masks"
                )
            from pytorch_distributed_train_tpu.ops.ring_attention import (
                ring_attention,
            )

            return ring_attention(
                q, k, v, mesh=cp.mesh, causal=causal, window=window,
                impl=impl, layout=cp.layout, context_axis=cp.context_axis,
                batch_axes=cp.batch_axes, tensor_axis=cp.tensor_axis,
            )
        if cp.impl == "ulysses":
            from pytorch_distributed_train_tpu.ops.ulysses import (
                ulysses_attention,
            )

            return ulysses_attention(
                q, k, v, mask=mask, mesh=cp.mesh, causal=causal,
                window=window, context_axis=cp.context_axis,
                batch_axes=cp.batch_axes,
                tensor_axis=cp.tensor_axis, impl=impl,
            )
        raise ValueError(f"unknown context_impl {cp.impl!r}")
    if impl in ("auto", "pallas") and segments is None:
        from pytorch_distributed_train_tpu.ops import flash_attention as _fa

        on_tpu = _on_tpu()
        if _fa.supported(q, k, v, causal=causal, mask=mask, window=window):
            # impl='pallas' forces the kernel anywhere (interpret mode off-TPU
            # — slow but exact, which is what tests and debugging want);
            # 'auto' uses it only on TPU where it pays off and the backend
            # can actually compile Mosaic (_pallas_usable).
            if impl == "pallas" or (
                on_tpu and _pallas_usable() and _fa.profitable(q)
            ):
                # GQA is native in the kernel (KV BlockSpec index_map
                # b // rep) — no expanded K/V copy in HBM.
                return _fa.flash_attention(q, k, v, causal=causal,
                                           window=window,
                                           interpret=not on_tpu)
        elif impl == "pallas":
            raise ValueError("pallas flash attention unsupported for these shapes")
    if impl == "chunked" or (impl == "auto" and q.shape[1] >= _AUTO_CHUNK_MIN_SEQ):
        # auto → chunked at training-length sequences when the Pallas kernel
        # didn't take the call above. Measured on v5e (BASELINE.md
        # 2026-07-30): llama seq2048 +11% tokens/sec AND fits shapes the
        # dense path OOMs on; BERT seq512 −3.6% (tile overhead) → dense
        # stays the short-seq default.
        return _chunked_attention(q, k, v, causal=causal, mask=mask,
                                  softmax_dtype=softmax_dtype, window=window,
                                  segments=segments)
    if segments is not None:
        seg_mask = (segments[:, None, :, None] == segments[:, None, None, :])
        mask = seg_mask if mask is None else (mask & seg_mask)
    return _xla_attention(q, k, v, causal=causal, mask=mask,
                          softmax_dtype=softmax_dtype, window=window)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _xla_attention(q, k, v, *, causal, mask, softmax_dtype, window=0):
    from pytorch_distributed_train_tpu.ops.cp_common import expand_kv_heads

    orig_dtype = q.dtype
    B, Sq, H, D = q.shape
    _, Sk, Hkv, _ = k.shape
    # GQA: repeat KV heads up to H (XLA fuses the broadcast into the matmul)
    k, v = expand_kv_heads(k, v, H)

    scale = 1.0 / jnp.sqrt(D).astype(softmax_dtype)
    # (B, H, Sq, Sk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=softmax_dtype)
    logits = logits * scale

    if causal:
        q_pos = jnp.arange(Sq)[:, None] + (Sk - Sq)  # align ends for KV-cache decode
        k_pos = jnp.arange(Sk)[None, :]
        causal_mask = q_pos >= k_pos
        if window:
            causal_mask &= (q_pos - k_pos) < window
        logits = jnp.where(causal_mask[None, None], logits, _neg_inf(softmax_dtype))
    if mask is not None:
        logits = jnp.where(mask, logits, _neg_inf(softmax_dtype))

    probs = jax.nn.softmax(logits, axis=-1).astype(orig_dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _neg_inf(dtype) -> jax.Array:
    return jnp.asarray(jnp.finfo(dtype).min, dtype)


# Query-chunk size for impl="chunked". 256 keeps the per-chunk logits tile
# MXU-friendly while bounding live attention memory to O(chunk * Sk).
_CHUNK_Q = 256

# impl="auto" switches from dense XLA to the chunked path at this query
# length (≥4 tiles — below that the map/remat overhead outweighs the
# saved HBM traffic; see the v5e llama/BERT measurements in BASELINE.md).
_AUTO_CHUNK_MIN_SEQ = 1024


def _chunked_attention(q, k, v, *, causal, mask, softmax_dtype,
                       chunk: int = _CHUNK_Q, window: int = 0,
                       segments=None):
    """Memory-efficient attention in pure XLA: flash-attention's streaming
    structure (process the score matrix in tiles, never materialise it
    whole) expressed as a sequential `lax.map` over query chunks with the
    chunk body rematerialised.

    Motivation (measured, BASELINE.md 2026-07-30): the plain XLA path keeps
    O(Sq*Sk) bf16 score/remat temps live through the backward — a ~1B llama
    at bs8/seq2048 needs 16.85G vs the chip's 15.75G HBM. Here the forward
    holds one (B, H, chunk, Sk) fp32 tile at a time, and `jax.checkpoint`
    on the body makes the backward recompute tiles instead of storing them
    — the same FLOPs-for-HBM trade the Pallas flash kernel makes, minus the
    hand-written kernel, so it compiles on any backend (including remote
    compilers that cannot take Mosaic, e.g. this sandbox's axon tunnel).

    Numerics match `_xla_attention` exactly per chunk: fp32 scores, full
    row softmax over Sk (no online rescaling needed — each query row sees
    all keys within its tile), output cast back to the input dtype.
    """
    from pytorch_distributed_train_tpu.ops.cp_common import expand_kv_heads

    orig_dtype = q.dtype
    B, Sq, H, D = q.shape
    _, Sk, _, _ = k.shape
    k, v = expand_kv_heads(k, v, H)
    if Sq <= chunk:
        if segments is not None:
            seg_mask = (segments[:, None, :, None]
                        == segments[:, None, None, :])
            mask = seg_mask if mask is None else (mask & seg_mask)
        return _xla_attention(q, k, v, causal=causal, mask=mask,
                              softmax_dtype=softmax_dtype, window=window)

    n_chunks = -(-Sq // chunk)
    pad = n_chunks * chunk - Sq
    q_padded = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
    if mask is not None and mask.ndim < 4:
        # Honor the dense path's broadcastable-mask contract: left-pad
        # dims exactly as numpy broadcasting against (B, H, Sq, Sk) would,
        # so dim 2 is the query axis for the tile slicing below.
        mask = mask.reshape((1,) * (4 - mask.ndim) + mask.shape)
    if mask is not None and mask.shape[2] > 1 and pad:
        # Keep tile slices aligned: dynamic_slice clamps at the edge, which
        # would shift the last tile's window. Padded rows are fully masked;
        # their (uniform-softmax) outputs are dropped by the final slice.
        mask = jnp.pad(mask, ((0, 0),) * 2 + ((0, pad), (0, 0)),
                       constant_values=False)
    # (n, B, chunk, H, D) — leading axis is the map axis
    q_tiles = q_padded.reshape(B, n_chunks, chunk, H, D).transpose(1, 0, 2, 3, 4)
    starts = jnp.arange(n_chunks) * chunk

    scale = 1.0 / jnp.sqrt(D).astype(softmax_dtype)
    k_pos = jnp.arange(Sk)[None, :]
    # Sliding window: each tile's queries only see keys in
    # [start - window + 1, start + chunk) — slice K/V to that static-width
    # band instead of scoring (and masking away) the whole key axis:
    # O(Sq * window) work, the compute win windowing exists for. Only when
    # no explicit mask rides along (its key axis would need slicing too).
    if segments is not None and pad:
        # padded query rows get segment id -1: they match nothing real
        seg_padded = jnp.pad(segments, ((0, 0), (0, pad)),
                             constant_values=-1)
    else:
        seg_padded = segments
    band_width = min(Sk, (window + chunk - 1)) if window else Sk
    use_band = (bool(window) and mask is None and segments is None
                and band_width < Sk)

    def body(args):
        q_tile, start = args
        if use_band:
            band_start = jnp.clip(start + (Sk - Sq) - (window - 1),
                                  0, Sk - band_width)
            k_t = jax.lax.dynamic_slice_in_dim(k, band_start, band_width, 1)
            v_t = jax.lax.dynamic_slice_in_dim(v, band_start, band_width, 1)
            k_pos_t = (band_start + jnp.arange(band_width))[None, :]
        else:
            k_t, v_t, k_pos_t = k, v, k_pos
        logits = jnp.einsum("bqhd,bkhd->bhqk", q_tile, k_t,
                            preferred_element_type=softmax_dtype) * scale
        q_pos = start + jnp.arange(chunk)[:, None] + (Sk - Sq)
        if causal:
            keep = q_pos >= k_pos_t
            if window:
                keep &= (q_pos - k_pos_t) < window
            logits = jnp.where(keep[None, None], logits,
                               _neg_inf(softmax_dtype))
        if mask is not None:
            # mask is (B, 1, Sq, Sk) or broadcastable; slice the query axis
            # when it is materialised, else broadcast as-is.
            if mask.shape[2] == 1:
                tile_mask = mask
            else:
                tile_mask = jax.lax.dynamic_slice_in_dim(mask, start, chunk,
                                                         axis=2)
            logits = jnp.where(tile_mask, logits, _neg_inf(softmax_dtype))
        if seg_padded is not None:
            # one (B, 1, chunk, Sk) segment tile at a time — never the
            # full (B, 1, Sq, Sk) mask (the whole point of this path)
            seg_q = jax.lax.dynamic_slice_in_dim(seg_padded, start, chunk,
                                                 axis=1)
            seg_tile = (seg_q[:, None, :, None]
                        == seg_padded[:, None, None, :Sk])
            logits = jnp.where(seg_tile, logits, _neg_inf(softmax_dtype))
        # Padded query rows (beyond Sq) mask everything out → uniform
        # softmax over garbage; harmless, dropped by the final slice.
        probs = jax.nn.softmax(logits, axis=-1).astype(orig_dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v_t)

    out_tiles = jax.lax.map(jax.checkpoint(body), (q_tiles, starts))
    out = out_tiles.transpose(1, 0, 2, 3, 4).reshape(B, n_chunks * chunk, H, D)
    return out[:, :Sq]
