"""Trainer: builds everything from a TrainConfig and runs the epoch/step loop.

The structural twin of the reference's train.py main() (SURVEY H1, §3.3):
build mesh ← (init_process_group) · model ← config · data · optimizer ·
restore ← checkpoint · loop{step, log, ckpt} · validate. Every phase maps to
its TPU-native mechanism per SURVEY §7.2.
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_train_tpu import faults as faults_lib
from pytorch_distributed_train_tpu import lora as lora_lib
from pytorch_distributed_train_tpu import losses as losses_lib
from pytorch_distributed_train_tpu import steps as steps_lib
from pytorch_distributed_train_tpu.checkpoint import (
    BestCheckpointTracker,
    CheckpointManager,
)
from pytorch_distributed_train_tpu.ckpt import build_checkpoint_manager
from pytorch_distributed_train_tpu.config import TrainConfig
from pytorch_distributed_train_tpu.data.datasets import build_dataset
from pytorch_distributed_train_tpu.data.pipeline import build_input_pipeline
from pytorch_distributed_train_tpu.models.registry import build_model
from pytorch_distributed_train_tpu.obs import cluster as cluster_lib
from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs import memory as memory_lib
from pytorch_distributed_train_tpu.obs import perf as perf_lib
from pytorch_distributed_train_tpu.obs import profiler as profiler_lib
from pytorch_distributed_train_tpu.obs import spans as spans_lib
from pytorch_distributed_train_tpu.obs import tracing
from pytorch_distributed_train_tpu.obs.goodput import GoodputTracker
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.optim import make_optimizer, plateau_scale
from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
from pytorch_distributed_train_tpu.parallel.partition import rules_for_model
from pytorch_distributed_train_tpu.sentinel import numeric as sentinel_numeric
from pytorch_distributed_train_tpu.train_state import DynamicScale, TrainState
from pytorch_distributed_train_tpu.utils import debug as debug_lib
from pytorch_distributed_train_tpu.utils import flops as flops_lib
from pytorch_distributed_train_tpu.utils.metrics import Meter, MetricLogger
from pytorch_distributed_train_tpu.utils.watchdog import FlightRecorder, Heartbeat


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None):
        # Goodput clock starts at construction: mesh/model/data/restore
        # time is the init bucket (obs/goodput.py) — a job that spends
        # minutes rebuilding state per restart should see it in the
        # summary, not have it vanish into pre-fit limbo.
        _t_init0 = time.perf_counter()
        self.goodput = GoodputTracker(t0=_t_init0)
        self.cfg = cfg
        # ---- event journal (obs/events.py): configured FIRST so every
        # later construction phase (fault schedule, data, restore) can
        # journal. PDTT_EVENTS_DIR (tpurun --events-dir) beats the
        # per-run default so agent + all hosts share one directory.
        self.journal = events_lib.configure(
            (cfg.obs.events_dir or os.environ.get(events_lib.ENV_VAR)
             or os.path.join(cfg.checkpoint.dir, "events"))
            if cfg.obs.events else None)
        # ---- distributed tracing (obs/tracing.py): spill beside the
        # journal, and stamp (gen, step) correlation tags on every span
        # so serving traces on a co-resident host line up against what
        # this trainer was doing — the ROADMAP-4 weight-sync debugging
        # contract. Step updates at the loop (cheap dict write).
        tracing.configure(
            cfg.obs.trace_dir or os.environ.get(tracing.ENV_DIR)
            or os.path.join(cfg.checkpoint.dir, "traces"),
            sample_pct=cfg.obs.trace_sample_pct,
            keep_slow_ms=cfg.obs.trace_keep_slow_ms)
        spans_lib.set_correlation_tags(
            gen=os.environ.get("RESTART_GENERATION", "0"))
        # ---- fault schedule + recovery policies (faults/): configured
        # before data/checkpoint construction so every fault point those
        # layers traverse is already armed. obs.fault_inject_at_step is
        # the deprecated single-kill hook, routed through the registry.
        self.faults = faults_lib.configure(
            tuple(cfg.faults.inject), seed=cfg.faults.seed,
            legacy_crash_step=cfg.obs.fault_inject_at_step)
        faults_lib.set_default_policy(faults_lib.RetryPolicy(
            max_attempts=cfg.faults.retry_max_attempts,
            base_delay_s=cfg.faults.retry_base_delay_s,
            max_delay_s=cfg.faults.retry_max_delay_s))
        if cfg.obs.debug_nans:
            debug_lib.enable_nan_debugging()
        cache_dir = cfg.obs.compile_cache_dir
        if cache_dir:
            # Per-worker subdir under tpurun: this container's jax loads
            # truncated cache entries without validation, so a worker
            # killed mid-cache-write (crash drill, SIGKILL escalation)
            # would poison every sibling and later generation sharing
            # the dir (CHANGES PR 3 gotcha). Worker id is stable across
            # restart generations, so each worker still reuses ITS cache.
            wid = os.environ.get("PROCESS_ID")
            if wid is not None:
                from pytorch_distributed_train_tpu.elastic import (
                    worker_cache_dir,
                )

                cache_dir = worker_cache_dir(cache_dir, wid)
        elif os.environ.get("PDTT_COMPILE_CACHE_DIR"):
            # tpurun --compile-cache-dir derived a per-worker dir for us
            cache_dir = os.environ["PDTT_COMPILE_CACHE_DIR"]
        if cache_dir:
            # Persistent XLA compile cache: restart-and-resume (the SPMD
            # elasticity model, SURVEY §5.3) skips the minutes-scale GSPMD
            # recompiles of large models.
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        if (getattr(cfg.optim, "swa_update_bn_batches", 0) > 0
                and cfg.optim.ema_decay == 0.0
                and getattr(cfg.optim, "swa_start_step", 0) == 0):
            raise ValueError(
                "optim.swa_update_bn_batches requires weight averaging "
                "(set optim.swa_start_step or optim.ema_decay) — "
                "silently ignoring the knob would ship stale-stats "
                "results the user believes were re-estimated")
        self.mesh = mesh if mesh is not None else build_mesh(cfg.mesh)
        self.batch_axes = tuple(cfg.mesh.batch_axes)
        self.model = build_model(cfg.model, cfg.precision,
                                 mesh=self.mesh, mesh_cfg=cfg.mesh)
        fused_model = getattr(cfg.model, "fused_lm_loss", False)
        if fused_model != (cfg.loss == "fused_causal_lm_xent"):
            raise ValueError(
                "model.fused_lm_loss and loss='fused_causal_lm_xent' must be "
                f"set together (got fused_lm_loss={fused_model}, "
                f"loss={cfg.loss!r}): the fused model returns CE sums, not "
                "logits, so no other loss can consume its output")
        if fused_model and cfg.model.name not in ("llama", "gpt2"):
            raise ValueError(
                f"fused_lm_loss is implemented for llama/gpt2, not "
                f"{cfg.model.name!r}")
        if (getattr(cfg.model, "quant_training", "")
                and cfg.model.name not in ("llama", "llama_pp", "gpt2")):
            raise ValueError(
                f"quant_training is implemented for llama/llama_pp/gpt2, "
                f"not {cfg.model.name!r} (other models would silently "
                "ignore the knob)")
        if (cfg.model.num_experts > 1
                and cfg.model.moe_router == "expert_choice"
                and cfg.loss in ("causal_lm_xent", "fused_causal_lm_xent")
                and not cfg.model.moe_router_allow_noncausal):
            raise ValueError(
                "moe_router='expert_choice' with a causal-LM loss leaks "
                "future tokens into routing (selection ranks over the whole "
                "flattened batch — ops/moe.py::expert_choice_dispatch). Use "
                "moe_router='topk', or set "
                "model.moe_router_allow_noncausal=true to accept the "
                "Zhou et al. 2022 caveat explicitly")
        if cfg.lora.rank > 0 and cfg.optim.name == "schedule_free_adamw":
            raise ValueError(
                "lora + schedule_free_adamw is unsupported: the eval-time "
                "x/y unwrap (optim.schedule_free_eval) cannot locate the "
                "ScheduleFreeState through the lora optimizer mask "
                "(optax.multi_transform nests per-label inner states)")
        self.teacher_fn = None
        if cfg.loss == "dpo":
            # Preference fine-tuning: distill.teacher_checkpoint names the
            # frozen REFERENCE policy (the pre-DPO model) — loaded through
            # the same teacher machinery, consumed by a different loss.
            if not cfg.distill.teacher_checkpoint:
                raise ValueError(
                    "loss='dpo' needs distill.teacher_checkpoint pointing "
                    "at the frozen reference policy's run directory")
            if getattr(cfg.model, "fused_lm_loss", False):
                raise ValueError(
                    "loss='dpo' needs per-position logits — set "
                    "model.fused_lm_loss=false")
            self.loss_fn = losses_lib.make_dpo_loss(cfg.dpo_beta)
            # DPO eval scores the same preference objective (the eval
            # step injects the reference logits too)
            self.eval_loss_fn = self.loss_fn
        else:
            self.loss_fn = losses_lib.get_loss_fn(
                cfg.loss, label_smoothing=cfg.label_smoothing)
            # Eval always scores the plain objective; the KD wrap below
            # only applies to training.
            self.eval_loss_fn = self.loss_fn
        if cfg.distill.teacher_checkpoint:
            from pytorch_distributed_train_tpu import distill as distill_lib

            t_model, t_vars, t_cfg = distill_lib.load_teacher(
                cfg.distill, cfg.precision, self.mesh,
                "causal_lm_xent" if cfg.loss == "dpo" else cfg.loss)
            t_dim = (t_cfg.num_classes if cfg.loss == "softmax_xent"
                     else t_cfg.vocab_size)
            s_dim = (cfg.model.num_classes if cfg.loss == "softmax_xent"
                     else cfg.model.vocab_size)
            if t_dim != s_dim:
                raise ValueError(
                    f"teacher output dim ({t_dim}) != student ({s_dim}) — "
                    "the teacher/reference and student distributions must "
                    "live on the same classes/vocabulary")
            self.teacher_fn = distill_lib.make_teacher_fn(t_model, t_vars)
            if cfg.loss != "dpo":
                self.loss_fn = losses_lib.make_distill_loss(
                    self.loss_fn, cfg.loss, cfg.distill.alpha,
                    cfg.distill.temperature)
        self.rules = rules_for_model(cfg.model.name)

        # ---- elastic world (docs/elastic.md): with data.elastic_shards
        # the LAUNCHER env (NUM_PROCESSES / PROCESS_ID) — not the jax
        # process world — decides the data sharding, so a degraded
        # tpurun generation reshards the input stream to the surviving
        # hosts. The GLOBAL batch stays fixed (per-host batch rescales);
        # the loaders reject a world the batch cannot divide by.
        self.data_world: tuple[int, int] | None = None
        if cfg.data.elastic_shards:
            from pytorch_distributed_train_tpu.elastic import elastic_world

            self.data_world = elastic_world()
            if self.data_world[0] < jax.process_count():
                # elastic_world() == (1, 0) here means the env contract
                # is ABSENT (valid alone, catastrophic combined with a
                # multi-process jax world: every host would load the
                # full global batch — silent record duplication).
                raise RuntimeError(
                    f"data.elastic_shards: launcher world "
                    f"{self.data_world[0]} < jax process world "
                    f"{jax.process_count()} — the NUM_PROCESSES/"
                    "PROCESS_ID env contract is missing or stale; "
                    "sharding by it would duplicate records across "
                    "hosts")
        self.world = (self.data_world[0] if self.data_world is not None
                      else jax.process_count())

        # ---- data
        dw = self.data_world or (None, None)
        self.train_ds = build_dataset(cfg.data, cfg.model, train=True)
        self.train_loader, self.train_epoch_fn = build_input_pipeline(
            self.train_ds, cfg.data, self.mesh, train=True,
            batch_axes=self.batch_axes,
            sync_check_every=cfg.obs.check_input_sync_every,
            num_hosts=dw[0], host_id=dw[1],
        )
        self.eval_ds = build_dataset(cfg.data, cfg.model, train=False)
        self.eval_loader, self.eval_epoch_fn = build_input_pipeline(
            self.eval_ds, cfg.data, self.mesh, train=False,
            batch_axes=self.batch_axes,
            num_hosts=dw[0], host_id=dw[1],
        )

        # ---- horizon
        self.steps_per_epoch = self.train_loader.steps_per_epoch
        if cfg.epochs > 0:
            self.total_steps = cfg.epochs * self.steps_per_epoch
        else:
            self.total_steps = cfg.total_steps

        # ---- optimizer (adapter-only masking, when LoRA is on, happens
        # inside make_optimizer so MultiSteps stays the outermost wrapper)
        self.tx, self.lr_schedule = make_optimizer(
            cfg.optim, self.total_steps, self.steps_per_epoch,
            param_mask=(lambda tx: lora_lib.mask_optimizer(tx, cfg.lora))
            if cfg.lora.rank > 0 else None,
            sentinel_cooldown=cfg.sentinel.enabled,
        )

        # ---- compute-graph optimization layer (train.* knobs; steps.py
        # + ops/fused_update.py; docs/performance.md "Compute side").
        # Every invalid combination is refused loudly at construction —
        # a knob that silently does nothing records wrong measurements.
        tcfg = cfg.train
        if tcfg.grad_accum_steps > 1:
            if cfg.optim.accum_steps > 1:
                raise ValueError(
                    "train.grad_accum_steps and optim.accum_steps both "
                    "accumulate gradients — they would compound; use one "
                    "(grad_accum_steps scans microbatches in-graph, "
                    "accum_steps runs MultiSteps micro-steps)")
            # The scan splits what the step SEES: the global batch under
            # GSPMD jit, but the PER-SHARD batch under shard_map
            # (overlap_collectives) — validate the right unit here, not
            # at trace time with a misleading size in the message.
            shards = 1
            if tcfg.overlap_collectives:
                for ax in self.batch_axes:
                    shards *= max(self.mesh.shape.get(ax, 1), 1)
            if cfg.data.batch_size % shards:
                raise ValueError(
                    f"global batch {cfg.data.batch_size} not divisible "
                    f"by the {shards}-way batch sharding "
                    f"({'x'.join(self.batch_axes)})")
            unit = cfg.data.batch_size // shards
            if unit % tcfg.grad_accum_steps:
                raise ValueError(
                    f"train.grad_accum_steps={tcfg.grad_accum_steps} must "
                    f"divide the "
                    f"{'per-shard' if shards > 1 else 'global'} batch "
                    f"{unit}"
                    + (f" (global {cfg.data.batch_size} over {shards} "
                       f"shards)" if shards > 1 else ""))
        self.fused_update = None
        if tcfg.fused_epilogue:
            from pytorch_distributed_train_tpu.optim import (
                fused_update_unsupported_reason,
                make_fused_update,
            )

            reason = fused_update_unsupported_reason(
                cfg.optim, has_param_mask=cfg.lora.rank > 0)
            if reason is not None:
                raise ValueError(f"train.fused_epilogue: {reason}")
            if cfg.optim.ema_decay > 0.0 or \
                    getattr(cfg.optim, "swa_start_step", 0) > 0:
                raise ValueError(
                    "train.fused_epilogue does not maintain the EMA/SWA "
                    "mirror — disable optim.ema_decay/swa_start_step")
            self.fused_update = make_fused_update(
                cfg.optim, self.lr_schedule,
                sentinel_cooldown=cfg.sentinel.enabled)
        if tcfg.overlap_collectives:
            if cfg.optim.offload_state:
                raise ValueError(
                    "train.overlap_collectives + optim.offload_state: the "
                    "shard_map step cannot stage host-memory opt state")
            for ax in ("stage", "tensor", "context", "expert"):
                if self.mesh.shape.get(ax, 1) != 1:
                    raise ValueError(
                        "train.overlap_collectives is the DDP analogue — "
                        "pure data parallelism over the batch axes; mesh "
                        f"axis {ax!r}={self.mesh.shape[ax]} shards the "
                        "model (GSPMD already overlaps those collectives)")

        # ---- state (sharded init: params materialize directly into their
        # mesh layout — no host-RAM staging of 7B params; SURVEY C13)
        self.rng = jax.random.PRNGKey(cfg.seed)
        init_rng, self.step_rng = jax.random.split(self.rng)
        state_shape = jax.eval_shape(self._init_state, init_rng)
        self.state_sharding = steps_lib.state_shardings(
            self.mesh, self.rules, state_shape,
            zero_stage=cfg.mesh.zero_stage,
        )
        opt_dev_sharding = self.state_sharding.opt_state
        if cfg.optim.offload_state:
            if jax.devices()[0].platform == "cpu":
                raise ValueError(
                    "optim.offload_state needs a TPU backend — the CPU "
                    "backend cannot execute host-memory placement "
                    "(annotate_device_placement)")
            self.state_sharding = steps_lib.offload_state_shardings(
                self.state_sharding)
        with self.mesh:
            self.state: TrainState = jax.jit(
                self._init_state, out_shardings=self.state_sharding
            )(init_rng)

        # ---- jitted steps
        from pytorch_distributed_train_tpu.ops.device_augment import (
            build_device_augment,
        )
        from pytorch_distributed_train_tpu.ops.mixup import build_mixup

        mixup = build_mixup(cfg.data, cfg.model, cfg.label_smoothing,
                            loss=cfg.loss)
        # Device-side augmentation (ops/device_augment.py): the dataset
        # decides applicability — only raw-u8 shippers get the transform
        # (host path byte-unchanged when data.device_augment is off).
        device_augment = build_device_augment(cfg.data, self.train_ds)
        param_transform = None
        if cfg.lora.rank > 0:
            param_transform = lambda p: lora_lib.merge(p, cfg.lora)  # noqa: E731
        reduce_grads = reduce_metrics = None
        self.grad_buckets = None
        if cfg.train.overlap_collectives:
            # Bucketed in-scan reduction (steps.overlap_grad_reducer):
            # buckets derived AOT from the params shape tree, reverse
            # parameter order, ~grad_bucket_mb each (DDP bucket_cap_mb).
            reduce_grads, self.grad_buckets = steps_lib.overlap_grad_reducer(
                state_shape.params, max(cfg.train.grad_bucket_mb, 1),
                self.batch_axes)
            reduce_metrics = steps_lib.metrics_reducer(self.batch_axes)
        train_step = steps_lib.make_train_step(
            self.model, self.loss_fn, self.tx,
            ema_decay=cfg.optim.ema_decay,
            swa_start=getattr(cfg.optim, "swa_start_step", 0),
            swa_every=getattr(cfg.optim, "swa_every", 1), mixup=mixup,
            device_augment=device_augment,
            module_grad_norms=cfg.obs.log_module_grad_norms,
            model_health=cfg.obs.model_health,
            param_transform=param_transform,
            teacher_fn=self.teacher_fn,
            numeric_guard=cfg.sentinel.enabled,
            grad_accum_steps=cfg.train.grad_accum_steps,
            fused_update=self.fused_update,
            reduce_grads=reduce_grads,
            reduce_metrics=reduce_metrics)
        if cfg.optim.offload_state:
            train_step = steps_lib.offload_opt_state(
                train_step, opt_dev_sharding, self.state_sharding.opt_state)
        if cfg.train.overlap_collectives:
            self.train_step = steps_lib.jit_overlap_train_step(
                train_step, self.mesh, self.state_sharding,
                self.batch_axes)
            if jax.process_index() == 0:
                print(f"[train] overlapped collectives: "
                      f"{len(self.grad_buckets)} grad bucket(s) x "
                      f"{cfg.train.grad_accum_steps} microbatch(es), "
                      f"bucket cap {cfg.train.grad_bucket_mb} MiB",
                      flush=True)
        else:
            self.train_step = steps_lib.jit_train_step(
                train_step, self.mesh, self.state_sharding, self.batch_axes,
            )
        self.eval_step = steps_lib.jit_eval_step(
            steps_lib.make_eval_step(
                self.model, self.eval_loss_fn,
                schedule_free=cfg.optim.name == "schedule_free_adamw",
                param_transform=param_transform,
                teacher_fn=self.teacher_fn if cfg.loss == "dpo" else None,
                device_augment=build_device_augment(cfg.data,
                                                    self.eval_ds)),
            self.mesh, self.state_sharding, self.batch_axes,
        )
        if cfg.lora.rank > 0 and jax.process_index() == 0:
            t, n = lora_lib.count_trainable(self.state.params, cfg.lora)
            print(f"[lora] rank={cfg.lora.rank} trainable {t:,} / "
                  f"{n:,} params ({100.0 * t / n:.2f}%)", flush=True)

        # ---- checkpoint + resume (auto is the default path, SURVEY §5.3b)
        # checkpoint.tiered selects the async tiered plane (ckpt/):
        # snapshot-only blocking at save boundaries, hot RAM/disk/peer
        # restore tiers, back-pressure drain re-attributed to the
        # ckpt.drain goodput bucket.
        # run_meta: every saved step records the world + global batch it
        # was trained under, so a resumed generation can tell a reshard
        # from a plain restart (and refuse a silently-changed global
        # batch — the one bookkeeping mistake that would corrupt LR/data
        # semantics without any error).
        self.ckpt = build_checkpoint_manager(
            cfg.checkpoint, cfg.to_json(), goodput=self.goodput,
            run_meta={"world": self.world,
                      "global_batch": cfg.data.batch_size})
        self.best_ckpt = (BestCheckpointTracker(cfg.checkpoint, cfg.to_json())
                          if cfg.checkpoint.best_metric else None)
        if (cfg.lora.rank > 0 and cfg.lora.base_checkpoint
                and (cfg.checkpoint.resume == "none"
                     or self.ckpt.latest_good_step() is None)):
            # Fresh LoRA run: pull the frozen base from the pretrained
            # checkpoint. A restarted run (resume enabled + own ckpt
            # present) skips this — its resume below restores
            # base+adapters together, and re-reading the (potentially
            # 7B-scale) source checkpoint only to overwrite it would
            # waste minutes of IO per gang restart. With resume='none'
            # the own ckpt is never restored, so warm-start must run.
            self._warm_start_lora_base()
        self.start_epoch = 0
        self.resumed = False  # did construction restore a checkpoint?
        resume_mode = cfg.checkpoint.resume
        if resume_mode != "none":
            if resume_mode in ("auto", cfg.checkpoint.dir):
                restored = self.ckpt.restore(self.state)
            else:
                # explicit path: warm-start from a DIFFERENT run's directory
                src_cfg = dataclasses.replace(cfg.checkpoint, dir=resume_mode,
                                              resume="none")
                src = CheckpointManager(src_cfg)
                restored = src.restore(self.state)
                src.close()
            if restored is not None:
                self.state, meta = restored
                self.resumed = True
                self.start_epoch = int(meta.get("epoch", 0))
                events_lib.emit("ckpt", "restore",
                                step=int(self.state.step),
                                epoch=self.start_epoch,
                                source=resume_mode)
                if jax.process_index() == 0:
                    print(f"[resume] restored step {int(self.state.step)} "
                          f"(epoch {self.start_epoch})", flush=True)
                self._note_reshard(meta)
            elif resume_mode not in ("auto",):
                raise FileNotFoundError(
                    f"checkpoint.resume={resume_mode!r} has no checkpoint to restore"
                )

        # ---- observability
        jsonl = cfg.obs.jsonl_path or f"{cfg.checkpoint.dir}/metrics.jsonl"
        tb_dir = f"{cfg.checkpoint.dir}/tb" if cfg.obs.tensorboard else ""
        self.logger = MetricLogger(jsonl, tb_dir)
        self.meter = Meter()
        # MFU accounting (utils/flops.py): analytic train FLOPs per
        # throughput item over the chip's bf16 peak; either side unknown
        # (unlisted model, CPU backend) disables the metric, never the run.
        self._flops_per_item = flops_lib.train_flops_per_item(
            cfg.model, getattr(cfg.data, "seq_len", None) or None)
        try:
            self._peak_flops = flops_lib.device_peak_flops(jax.devices()[0])
        except Exception:
            self._peak_flops = None
        self.recorder = FlightRecorder(dump_dir=cfg.checkpoint.dir)
        self.recorder.install_signal_dump()
        # Graceful preemption (faults/preemption.py): SIGTERM sets a
        # flag; the step loop checkpoints and exits cleanly. Composes
        # with the dump handler above in either install order — the
        # dump still happens, but the loop owns process exit.
        self.preempt = None
        self._preempted = False
        if cfg.faults.graceful_preemption:
            from pytorch_distributed_train_tpu.faults.preemption import (
                PreemptionHandler,
            )

            self.preempt = PreemptionHandler()
            self.preempt.install()
        self.heartbeat = Heartbeat(cfg.obs.heartbeat_timeout_s, self.recorder)
        # ---- managed profiler plane (obs/profiler.py): bounded capture
        # windows on cadence / on demand / on anomaly; the legacy
        # obs.profile_* fixed window rides through it as a shim.
        self.profiler = profiler_lib.ManagedProfiler(
            cfg.obs, run_dir=cfg.checkpoint.dir)
        self.profiler.start()
        # ---- unified obs layer (obs/): spans + registry + goodput.
        # One process-wide span ring — checkpoint saves, data producer
        # threads and the step loop interleave on a single exported
        # timeline; the watchdog dumps it on abort next to its events.
        self.spans = spans_lib.get_recorder()
        self.recorder.attach_spans(self.spans)
        self.registry = get_registry()
        self._step_hist = self.registry.histogram(
            "train_step_seconds",
            help="wall seconds between consecutive train-step completions "
                 "(meter intervals; excludes compile and eval gaps)")
        self.metrics_server = None
        if cfg.obs.metrics_port:
            from pytorch_distributed_train_tpu.obs.exposition import (
                MetricsServer,
            )

            try:
                self.metrics_server = MetricsServer(cfg.obs.metrics_port)
            except OSError:
                # Port collision: obs.metrics_port is one shared config
                # value but several workers can share a host (tpurun
                # --nprocs > 1). The sidecar is a diagnostic surface —
                # crashing the trainer over it would be backwards; fall
                # back to an ephemeral port and publish the ACTUAL port
                # through the store endpoint record below.
                self.metrics_server = MetricsServer(0)
                print(f"[obs] metrics port {cfg.obs.metrics_port} in use "
                      f"(another local worker?); bound ephemeral port "
                      f"{self.metrics_server.port} instead", flush=True)
            # POST /profile on the sidecar opens a TIME-bounded capture
            # (capture_for_seconds, not a step window): the route's
            # whole point is poking a run that may be wedged, and a
            # step-windowed request would wait forever on a step loop
            # that never advances.
            from pytorch_distributed_train_tpu.obs import exposition

            self._profile_trigger = (
                lambda: self.profiler.capture_for_seconds(10.0,
                                                          reason="http"))
            exposition.set_profile_trigger(self._profile_trigger)
            if jax.process_index() == 0:
                print(f"[obs] /metrics on port {self.metrics_server.port}",
                      flush=True)
            # Self-register the scrape endpoint with the launcher store
            # (elastic.publish_obs_endpoint) so the fleet collector
            # discovers this host without static config — the ACTUAL
            # bound port, which may differ from obs.metrics_port after
            # the collision fallback above. Best-effort: no store (not
            # under tpurun) just means no fleet discovery.
            try:
                from pytorch_distributed_train_tpu import elastic, store_plane

                store = store_plane.resilient_worker_store(
                    name="trainer-advertise")
                if store is not None:
                    addr = (f"{elastic.routable_host('')}"
                            f":{self.metrics_server.port}")
                    elastic.publish_obs_endpoint(store, "trainer", addr)
                    store.close()
                    print(f"[obs] registered fleet endpoint {addr}",
                          flush=True)
            except Exception:
                pass
        self._stepped = False  # first train_step call = compile bucket
        # Eval's share of the process-global input-stage stats
        # (obs/perf.py), snapshot-deltas around evaluate(): the summary
        # stage keys and the ledger's stall_split must blame the TRAIN
        # pipeline — the thing input_stall measures — not a large eval
        # set's decode time. (Approximation: the train producer keeps
        # refilling its bounded queue during eval; the error is capped
        # by the prefetch depth in batches.)
        self._eval_stage_s = {s: 0.0 for s in perf_lib.STAGES}
        # ---- training health sentinel (sentinel/): numeric plane state
        # (the in-graph gate is already inside the jitted step; this is
        # the host-side spike window + rewind bookkeeping) and the
        # cross-host liveness plane (store heartbeats + hang monitor).
        self._sentinel_on = cfg.sentinel.enabled
        self._spike = None
        self._bad_streak = 0
        self._rewinds = 0
        self._sentinel_skipped = 0
        self._sentinel_aborted = False
        if self._sentinel_on:
            self._spike = sentinel_numeric.SpikeDetector(
                window=cfg.sentinel.spike_window,
                sigma=cfg.sentinel.spike_sigma,
                min_samples=cfg.sentinel.spike_min_samples,
                min_rel=cfg.sentinel.spike_min_rel)
        # ---- model-health monitor (obs/model_health.py): per-series
        # spike detection over the host metrics record at log cadence —
        # divergence early warning on the training-dynamics telemetry
        # the in-graph pass (ops/model_health.py) lands in the step
        # metrics. Arms the SAME rewind path as the loss sentinel, but
        # fires on the precursors (grad/update norms, reward/KL drift)
        # steps before the loss moves. Independent of sentinel.enabled:
        # the monitor reads metrics already on host, no extra sync.
        self.health = None
        if cfg.obs.model_health:
            from pytorch_distributed_train_tpu.obs import (
                model_health as model_health_lib,
            )

            self.health = model_health_lib.ModelHealthMonitor(
                profiler=self.profiler)
        self.liveness = None
        if cfg.sentinel.hang_timeout_s > 0:
            from pytorch_distributed_train_tpu.sentinel.liveness import (
                LivenessPlane,
            )

            plane = LivenessPlane(
                hang_timeout_s=cfg.sentinel.hang_timeout_s,
                poll_s=cfg.sentinel.hang_poll_s,
                exit_code=cfg.sentinel.hang_exit_code,
                every_steps=cfg.sentinel.heartbeat_every_steps,
                recorder=self.recorder, spans=self.spans)
            if plane.start():
                self.liveness = plane
                print(f"[sentinel] liveness plane up (host {plane.rank}/"
                      f"{plane.world}, timeout "
                      f"{cfg.sentinel.hang_timeout_s}s)", flush=True)
        events_lib.emit("lifecycle", "trainer_init",
                        step=int(self.state.step), resumed=self.resumed,
                        world=self.world,
                        init_s=round(time.perf_counter() - _t_init0, 3))
        self.goodput.account("init", time.perf_counter() - _t_init0)

    # ------------------------------------------------------------------ init
    def _note_reshard(self, meta: dict) -> None:
        """Elastic reshard bookkeeping at restore time (docs/elastic.md).

        The checkpoint's run_meta says what world/global-batch it was
        written under. A changed WORLD is the supported reshard: the
        restore above already re-derived shardings for the new mesh and
        the loaders already recomputed per-host shards — journal it
        (the event the acceptance drill and timeline_report look for)
        and carry on. A changed GLOBAL BATCH under elastic_shards is
        refused loudly: the documented policy keeps the global batch
        fixed across generations (per-host batch rescales), because a
        silently different global batch shifts the LR schedule's
        step<->data mapping and every union-of-shards guarantee."""
        saved_world = meta.get("world")
        saved_gb = meta.get("global_batch")
        if (self.cfg.data.elastic_shards and saved_gb is not None
                and int(saved_gb) != int(self.cfg.data.batch_size)):
            raise ValueError(
                f"elastic resume with a different GLOBAL batch "
                f"(checkpoint: {saved_gb}, config: "
                f"{self.cfg.data.batch_size}): the reshard policy keeps "
                "the global batch fixed and rescales the per-host batch "
                "— change data.batch_size back, or start a fresh run")
        if saved_world is None or int(saved_world) == int(self.world):
            return
        detail = dict(from_world=int(saved_world), to_world=int(self.world),
                      global_batch=int(self.cfg.data.batch_size),
                      devices=jax.device_count())
        events_lib.emit("elastic", "reshard", step=int(self.state.step),
                        **detail)
        if getattr(self, "recorder", None) is not None:
            self.recorder.record("reshard", int(self.state.step), **detail)
        print(f"[elastic] resharded restore: checkpoint written on world "
              f"{saved_world}, resuming on world {self.world} "
              f"(global batch {self.cfg.data.batch_size} fixed; per-host "
              f"batch {self.cfg.data.batch_size // max(self.world, 1)})",
              flush=True)

    def _warm_start_lora_base(self):
        """lora.base_checkpoint: restore the BASE params subtree from a
        pretrained run's latest checkpoint into this run's (adapter-
        injected) state. Adapters keep their fresh identity init, so the
        warm-started model is exactly the pretrained model at step 0."""
        cfg = self.cfg
        src_cfg = dataclasses.replace(
            cfg.checkpoint, dir=cfg.lora.base_checkpoint, resume="none")
        src = CheckpointManager(src_cfg)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding),
            self.state.params)
        base = src.restore_params_only(lora_lib.strip_abstract(abstract))
        src.close()
        if base is None:
            raise FileNotFoundError(
                f"lora.base_checkpoint={cfg.lora.base_checkpoint!r} has no "
                "checkpoint to warm-start from")
        self.state = self.state.replace(
            params=lora_lib.transplant_base(self.state.params, base))
        if jax.process_index() == 0:
            print(f"[lora] warm-started base params from "
                  f"{cfg.lora.base_checkpoint}", flush=True)

    def _init_state(self, rng):
        dummy = self._dummy_inputs()
        variables = self.model.init({"params": rng}, *dummy, train=False)
        params = variables["params"]
        if self.cfg.lora.rank > 0:
            params = lora_lib.inject(
                jax.random.fold_in(rng, 0x10FA), params, self.cfg.lora)
        batch_stats = variables.get("batch_stats", {})
        ds = None
        ls = self.cfg.precision.loss_scale
        if ls == "dynamic":
            ds = DynamicScale.create(
                self.cfg.precision.loss_scale_init,
                self.cfg.precision.loss_scale_growth_interval,
            )
        elif ls != "none":
            # static scale: fixed value, never grows (still halves on
            # overflow as a safety net, like GradScaler with growth off)
            ds = DynamicScale.create(float(ls), growth_interval=2**31 - 1)
        return TrainState.create(
            params=params, tx=self.tx, batch_stats=batch_stats,
            dynamic_scale=ds, ema=self.cfg.optim.ema_decay > 0.0,
            swa=getattr(self.cfg.optim, "swa_start_step", 0) > 0,
        )

    def _dummy_inputs(self) -> tuple:
        return steps_lib.dummy_inputs(self.cfg.loss, self.cfg.model,
                                      self.cfg.data)

    @property
    def items_per_step(self) -> int:
        if self.cfg.loss == "softmax_xent":
            return self.cfg.data.batch_size  # images/step
        if self.cfg.loss == "dpo":  # each row is a (chosen, rejected) pair
            return 2 * self.cfg.data.batch_size * self.cfg.data.seq_len
        return self.cfg.data.batch_size * self.cfg.data.seq_len  # tokens/step

    # ------------------------------------------------------------------ loop
    def compile_report(self, batch_size: int | None = None) -> dict:
        """AOT-compile the train step (no step runs) and return the
        compiler's per-device memory accounting — the `--compile-only`
        "will this config fit" probe (the torch-world analogue is running
        a step and reading torch.cuda.memory_summary; XLA can answer
        before any step executes). Args/outputs alias through donation,
        so resident ≈ args + temps. ``batch_size`` overrides the config's
        GLOBAL batch for this lowering only (the state and step function
        are batch-shape-agnostic — find_batch_size re-lowers at many
        sizes off one Trainer). Backend caveat: XLA:CPU gives remat
        regions distinct temp allocations (see tools/memfit_7b.py) — on
        CPU treat temps as an upper bound."""
        first = next(iter(self.train_loader.epoch(0)))
        gb = batch_size or self.cfg.data.batch_size
        batch = {
            k: jax.ShapeDtypeStruct((gb,) + np.asarray(v).shape[1:],
                                    np.asarray(v).dtype)
            for k, v in first.items()
        }
        t0 = time.time()
        compiled = self.train_step.lower(
            self.state, batch, self.step_rng).compile()
        out = {"compile_s": round(time.time() - t0, 1),
               "n_devices": jax.device_count(),
               "global_batch": gb}
        try:
            ma = compiled.memory_analysis()
            out.update(
                arg_bytes=int(ma.argument_size_in_bytes),
                out_bytes=int(ma.output_size_in_bytes),
                temp_bytes=int(ma.temp_size_in_bytes),
                resident_bytes=int(ma.argument_size_in_bytes
                                   + ma.temp_size_in_bytes),
            )
        except Exception as e:  # pragma: no cover - backend-dependent
            out["memory_analysis_error"] = f"{type(e).__name__}: {e}"
        return out

    def find_batch_size(self, budget_bytes: int | None = None,
                        max_global: int = 1 << 20) -> dict:
        """Largest fitting GLOBAL batch by AOT memory accounting (the
        torch-world auto_scale_batch_size, but from the compiler instead
        of OOM-probing real steps — no device memory is ever touched).

        Doubles from the configured batch while the compiled step's
        per-device resident bytes fit ``budget_bytes`` (default: the
        device's reported memory limit), then bisects. Candidates stay
        multiples of the mesh's batch-axis extent (data x fsdp) so every
        probe is a shardable shape. Returns {fits: [...probes...],
        best_global, best_per_chip, budget_bytes}; a config whose
        CONFIGURED batch already exceeds the budget reports best 0."""
        if budget_bytes is None:
            stats = jax.local_devices()[0].memory_stats() or {}
            budget_bytes = stats.get("bytes_limit")
            if not budget_bytes:
                raise ValueError(
                    "device reports no memory limit (CPU backend?) — "
                    "pass an explicit budget (--hbm-gb)")
        # Batch-axis extent from the BUILT mesh (config axes may be -1 =
        # fill-with-remaining-devices).
        unit = 1
        for ax in ("data", "fsdp"):
            unit *= max(self.mesh.shape.get(ax, 1), 1)

        probes: list[dict] = []

        def fits(gb: int) -> bool:
            rep = self.compile_report(batch_size=gb)
            rep["fits"] = (rep.get("resident_bytes", budget_bytes + 1)
                           <= budget_bytes)
            probes.append(rep)
            if jax.process_index() == 0:
                print(f"[find-batch-size] global={gb} resident="
                      f"{rep.get('resident_bytes', -1) / 1024**3:.2f} GiB "
                      f"budget={budget_bytes / 1024**3:.2f} GiB "
                      f"fits={rep['fits']}", flush=True)
            return rep["fits"]

        base = max(self.cfg.data.batch_size // unit, 1) * unit
        lo = 0
        gb = base
        while gb <= max_global and fits(gb):
            lo, gb = gb, gb * 2
        if lo == 0:  # configured batch itself does not fit
            return {"budget_bytes": budget_bytes, "best_global": 0,
                    "best_per_chip": 0, "probes": probes}
        hi = gb  # known not to fit (or beyond max_global)
        # bisect on multiples of `unit` in (lo, hi)
        while hi - lo > unit:
            mid = ((lo + hi) // 2) // unit * unit
            if mid in (lo, hi):
                break
            if fits(mid):
                lo = mid
            else:
                hi = mid
        return {"budget_bytes": budget_bytes, "best_global": lo,
                "best_per_chip": lo // max(jax.device_count(), 1),
                "probes": probes}

    def fit(self, max_steps: int | None = None) -> TrainState:
        cfg = self.cfg
        limit = min(self.total_steps, max_steps or self.total_steps)
        step = int(self.state.step)
        epoch = self.start_epoch
        t_start = time.time()
        events_lib.emit("lifecycle", "fit_start", step=step, limit=limit,
                        epoch=epoch)
        try:
            while step < limit:
                self.recorder.record("epoch_start", step, epoch=epoch)
                # Mid-epoch resume: continue the epoch's batch stream at the
                # restored step's offset instead of replaying it (the
                # per-batch rng seeding makes this exact — data/pipeline.py).
                start_b = max(0, step - epoch * self.steps_per_epoch)
                if start_b >= self.steps_per_epoch:
                    start_b = 0  # stale epoch meta; just run a fresh epoch
                rewound = False
                for batch in self._timed_batches(
                        self.train_epoch_fn(epoch, start_b)):
                    if step >= limit:
                        break
                    self.profiler.on_step(step)
                    # Sentinel drill points (flag-kind: firing only
                    # reports a match; the corruption is ours to stage).
                    # step.nan@step=N poisons the batch of the step that
                    # completes as N+1 — the in-graph guard must then
                    # skip exactly that update. step.loss_spike inflates
                    # only the OBSERVED loss (detection drill; params
                    # untouched).
                    inflate_loss = self.faults.maybe_fire(
                        "step.loss_spike", step=step)
                    # step.grad_spike inflates only the OBSERVED grad/
                    # update telemetry (post-backward, pre-anything the
                    # monitor reads) — the early-warning drill: the
                    # model-health plane must fire on it while the loss
                    # stays healthy, so the sentinel never trips.
                    inflate_grads = self.faults.maybe_fire(
                        "step.grad_spike", step=step)
                    if self.faults.maybe_fire("step.nan", step=step):
                        batch = _poison_batch_nan(batch)
                    # First execution per process = jit trace + compile
                    # (+ one step); goodput attributes it to the compile
                    # bucket — recompile cost on restart-heavy jobs is
                    # precisely what goodput accounting exists to show.
                    is_first = not self._stepped
                    t_body = time.perf_counter()
                    # (gen, step) correlation tag: every span completed
                    # from here on — step-loop, ckpt, producer threads —
                    # carries the trainer's position, the id serving
                    # traces correlate against (obs/tracing.py).
                    spans_lib.set_correlation_tags(step=step)
                    with self.spans.span(
                            "train.compile" if is_first else "train.step",
                            step=step):
                        self.state, metrics = self.train_step(
                            self.state, batch, self.step_rng
                        )
                    self._stepped = True
                    if inflate_loss:
                        # step.loss_spike drill: corrupt the OBSERVED
                        # loss everywhere one observation is read —
                        # the log record, the scrape mirror the fleet
                        # collector reads, and the sentinel below all
                        # see the same spike; params stay healthy.
                        # (Lazy jnp multiply: no device sync here.)
                        metrics = dict(metrics,
                                       loss=metrics["loss"] * 1e6)
                    if inflate_grads:
                        # step.grad_spike drill: same observation-only
                        # stance — every grad/update telemetry reader
                        # (log record, scrape mirror, fleet collector,
                        # model-health monitor) sees the spike; params
                        # and the loss stay healthy. (Lazy jnp multiply:
                        # no device sync here.)
                        metrics = {
                            k: (v * 1e3 if k.startswith(
                                ("grad_norm", "update_norm",
                                 "update_ratio")) else v)
                            for k, v in metrics.items()}
                    # Host-side step counter: int(state.step) every step
                    # would sync the device and serialize async dispatch
                    # (the jitted step increments state.step identically,
                    # including loss-scale skip steps).
                    step += 1
                    self._maybe_inject_fault(step)
                    self._maybe_inject_stall(step)
                    dt_tick = self.meter.tick()
                    if dt_tick is not None:
                        self._step_hist.observe(dt_tick)
                        # step-time regression detector (anomaly plane):
                        # a meter tick that spikes off the rolling
                        # median+MAD baseline journals an anomaly and
                        # (opt-in) opens a capture window
                        self.profiler.observe_step_time(dt_tick, step)
                    if dt_tick is None:
                        # Priming tick (first step after a clock reset —
                        # epoch boundary or mid-epoch eval): its interval
                        # is excluded from meter.total_s, so drop the
                        # matching stall seconds (the producer cold-start
                        # wait) from the numerator too. Numerator and
                        # denominator must cover the SAME intervals or
                        # input_stall_pct can exceed 100% and spuriously
                        # fail the sustained drill's <5% gate.
                        stats = getattr(self.train_loader, "stall_stats",
                                        None)
                        if stats is not None:
                            self._stall_prev = (stats.wait_s,
                                                self.meter.total_s)
                    self.heartbeat.beat()
                    if self.liveness is not None:
                        self.liveness.beat(step)
                    self.recorder.record("step", step)
                    if step % cfg.obs.log_every_steps == 0 or step == limit:
                        host_rec = self._log_train(step, metrics)
                        if (self.health is not None
                                and self.health.observe(step, host_rec)):
                            # Early-warning rewind: the model-health
                            # monitor armed on divergence PRECURSORS
                            # (grad/update norms, reward/KL) — same
                            # restore+cooldown path as the loss
                            # sentinel, steps earlier.
                            step = self._sentinel_rewind(step)
                            epoch = step // max(self.steps_per_epoch, 1)
                            self.meter.reset_clock()
                            rewound = True
                            break
                    # The step bucket closes AFTER the (cadenced) log:
                    # _log_train's device sync is where async-dispatched
                    # compute gets waited on host-side, and that wait is
                    # step time, not idle.
                    self.goodput.account(
                        "compile" if is_first else "step",
                        time.perf_counter() - t_body)
                    if self._sentinel_on and self._sentinel_observe(
                            step, metrics):
                        # Auto-rewind: BEFORE the cadence save below, so
                        # the diverged state is never checkpointed on
                        # the way out. The while loop re-enters with the
                        # rewound step and the exact mid-epoch
                        # start_batch fast-forward.
                        step = self._sentinel_rewind(step)
                        epoch = step // max(self.steps_per_epoch, 1)
                        self.meter.reset_clock()
                        rewound = True
                        break
                    with self.goodput.measure("ckpt"):
                        # A state under suspicion (mid bad-streak: spiking
                        # but finite, so updates DID apply) must not be
                        # checkpointed — the coming rewind would otherwise
                        # restore the very divergence it escapes.
                        if self._bad_streak == 0 and self.ckpt.maybe_save(
                                self.state, epoch=epoch, step=step):
                            self.recorder.record("ckpt", step)
                            events_lib.emit("ckpt", "save", step=step,
                                            epoch=epoch)
                            if self.liveness is not None:
                                # A synchronous cadence save (or a tiered
                                # back-pressure drain) can outlast
                                # hang_timeout_s on a loaded host; saving
                                # is progress, not a wedge.
                                self.liveness.pulse()
                    if (cfg.eval_every_steps and
                            step % cfg.eval_every_steps == 0):
                        with self.goodput.measure("eval"):
                            self.evaluate(step)
                        # Mid-epoch eval: keep its wall time out of the
                        # step-time percentiles AND the input-stall
                        # denominator (meter.total_s).
                        self.meter.reset_clock()
                    if self.preempt is not None and self.preempt.requested:
                        # Graceful preemption: stop at this step boundary;
                        # fit()'s finally force-saves the synchronized
                        # checkpoint and the summary carries the marker.
                        self._preempted = True
                        self.recorder.record("preempt", step)
                        events_lib.emit("preempt", "sigterm", step=step)
                        if jax.process_index() == 0:
                            print(f"[preempt] stopping at step {step}; "
                                  "checkpointing and exiting cleanly",
                                  flush=True)
                        break
                if self._preempted:
                    break
                if rewound:
                    continue  # re-enter at the restored step, not a new epoch
                epoch += 1
                if not cfg.eval_every_steps:
                    # every epoch boundary INCLUDING the last: the final
                    # validation metric is the acceptance-matrix number
                    with self.goodput.measure("eval"):
                        self.evaluate(step)
                self.meter.reset_clock()  # epoch boundary: don't count eval time
            if (not self._preempted
                    and getattr(cfg.optim, "swa_update_bn_batches", 0) > 0
                    and self.state.ema_params is not None
                    and self.state.batch_stats
                    and (self.state.swa_count is None
                         or int(self.state.swa_count) > 0)):
                # torch swa_utils recipe: averaged weights need freshly
                # estimated BN stats. Guards: an SWA run that never
                # reached swa_start has an INIT-weights mirror — stats
                # estimated under it would poison the checkpoint. The
                # fresh stats exist for the MIRROR; the eval (logged
                # under eval_swa, the deliverable metric — also what the
                # best-checkpoint tracker sees) runs on them, then the
                # trajectory stats come back so the cadence checkpoint
                # stays consistent with state.params for resume (torch
                # keeps swa_model's BN stats separate for the same
                # reason).
                trajectory_stats = self.state.batch_stats
                self.update_bn(cfg.optim.swa_update_bn_batches)
                self.evaluate(step, prefix="eval_swa")
                self.state = self.state.replace(
                    batch_stats=trajectory_stats)
        finally:
            self.heartbeat.stop()
            # A capture window still open at the horizon (or on an
            # abort) must stop + summarize NOW — an unterminated
            # profiler session would leak into teardown.
            self.profiler.finish(step)
            # NOTE: the liveness plane deliberately OUTLIVES fit() (it
            # stops in close()): a multi-host job that finished its loop
            # can still wedge in the final synchronized save or in a
            # peer's teardown barrier, and the hang monitor must keep
            # watching exactly through that window.
            if self.liveness is not None:
                self.liveness.pulse()  # the final save can be minutes-long
            with self.goodput.measure("ckpt"):
                # A sentinel abort (rewind budget exhausted) means the
                # live state is known-diverged: force-saving it would
                # make it the newest verified checkpoint and trap every
                # later generation in a restore/diverge loop.
                if not self._sentinel_aborted:
                    if self.ckpt.save(self.state, epoch=epoch, force=True,
                                      step=step):
                        events_lib.emit("ckpt", "save", step=step,
                                        epoch=epoch, final=True)
                self.ckpt.wait()
            if self.best_ckpt is not None:
                self.best_ckpt.close()
            stage_s = self._train_stage_seconds()
            self.logger.log(
                step,
                {"wall_time_s": time.time() - t_start,
                 "preempted": int(self._preempted),
                 "rewinds": self._rewinds,
                 "sentinel_skipped_steps": self._sentinel_skipped,
                 # staged input breakdown (obs/perf.py): the per-stage
                 # split of the TRAIN host-pipeline work behind
                 # input_stall (eval's share subtracted)
                 **{f"input_stage_s_{k}": round(v, 4)
                    for k, v in stage_s.items() if v > 0},
                 **self._input_plane_metrics(),
                 **self.meter.percentiles(), **self.goodput.snapshot()},
                prefix="summary",
            )
            self._append_perf_ledger(step)
            self.logger.close()
            self._dump_trace()
            events_lib.emit("lifecycle", "fit_end", step=step,
                            preempted=self._preempted,
                            rewinds=self._rewinds,
                            wall_s=round(time.time() - t_start, 3))
        return self.state

    def _input_plane_metrics(self) -> dict:
        """Input-plane counters for the summary record (ISSUE 12):
        shared-memory pool occupancy/batches and packed-cache hit
        activity, read back from the registry the pool/cache write
        into. Zero-activity keys are omitted — a run without the pool
        or cache keeps its summary line unchanged."""
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        reg = get_registry()
        out = {}
        for key, name, kind in (
                ("input_worker_occupancy", "input_worker_occupancy", "g"),
                ("input_worker_batches", "input_worker_batches_total", "f"),
                ("input_effective_workers", "input_effective_workers", "f"),
                ("packed_cache_hits", "packed_cache_hits_total", "f"),
                ("packed_cache_misses", "packed_cache_misses_total", "f"),
                ("packed_cache_records_read",
                 "packed_cache_records_read_total", "f"),
        ):
            v = (reg.get_value(name) if kind == "g"
                 else reg.family_total(name))
            if v:
                out[key] = round(float(v), 4)
        return out

    def _train_stage_seconds(self) -> dict:
        """The TRAIN pipeline's share of the process-global input-stage
        seconds: global totals minus the eval deltas accumulated around
        evaluate() (obs/perf.py stage vocabulary, floored at 0)."""
        out = {}
        for k, v in perf_lib.get_input_stats().snapshot().items():
            out[k] = max(0.0, v - self._eval_stage_s.get(k, 0.0))
        return out

    def _append_perf_ledger(self, step: int) -> None:
        """One perf-ledger row per fit() (rank 0): throughput, MFU,
        goodput and the stall-stage split — the trainer-side feed of the
        bench-history regression gate (obs/perf.py, docs/performance.md).
        Best-effort: the ledger must never fail the run."""
        cfg = self.cfg
        if not cfg.obs.perf_ledger or jax.process_index() != 0:
            return
        try:
            tput = self.meter.throughput(self.items_per_step)
            if tput is None:
                return  # no timed steps (smoke construction, 0-step fit)
            unit = "images" if cfg.loss == "softmax_xent" else "tokens"
            per_chip = tput / jax.device_count()
            mfu = flops_lib.mfu_pct(per_chip, self._flops_per_item,
                                    self._peak_flops)
            goodput = self.goodput.snapshot()
            path = (cfg.obs.perf_ledger_path
                    or os.environ.get(perf_lib.ENV_LEDGER)
                    or os.path.join(cfg.checkpoint.dir,
                                    "perf_ledger.jsonl"))
            perf_lib.PerfLedger(path).append(
                f"{cfg.model.name}_train_{unit}_per_sec_per_chip",
                round(per_chip, 2), unit=f"{unit}/sec/chip",
                source="trainer", config=cfg.to_json(),
                mfu_pct=None if mfu is None else round(mfu, 2),
                goodput_pct=goodput.get("goodput_pct"),
                stall_split=perf_lib.normalize_split(
                    self._train_stage_seconds()) or None,
                step=step)
        except Exception as e:
            print(f"[perf-ledger] trainer append failed "
                  f"({type(e).__name__}: {e})", flush=True)

    def _timed_batches(self, it):
        """Yield from the epoch iterator, accounting time blocked in its
        next() to the goodput input_stall bucket — the host-pipeline wait
        as the STEP LOOP experiences it (device_put assembly included),
        complementing StallStats' producer-queue view."""
        it = iter(it)
        _done = object()
        try:
            while True:
                t0 = time.perf_counter()
                batch = next(it, _done)
                self.goodput.account("input_stall",
                                     time.perf_counter() - t0)
                if batch is _done:
                    return
                yield batch
        finally:
            # Propagate early exits (step cap break) to the underlying
            # generator NOW — device_prefetch's finally stops the
            # producer thread; leaving that to GC would leak it until
            # collection.
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _dump_trace(self) -> None:
        """Write the host span ring as Chrome trace.json (process 0).
        Best-effort: observability must never fail the run."""
        if jax.process_index() != 0:
            return
        path = self.cfg.obs.trace_path or os.path.join(
            self.cfg.checkpoint.dir, "trace.json")
        try:
            self.spans.dump_chrome_trace(path)
        except Exception:
            pass  # incl. unserializable span args — never fail the run

    def _log_train(self, step: int, metrics: dict) -> dict:
        """Build + emit the host-side train record; returns it so the
        fit loop can feed the model-health monitor without a second
        device transfer."""
        host = {k: float(np.asarray(v)) for k, v in metrics.items()}
        # the schedule counts optimizer updates, not micro-steps
        host["lr"] = float(self.lr_schedule(step // max(self.cfg.optim.accum_steps, 1)))
        if self.cfg.optim.plateau_factor > 0:
            scale = plateau_scale(self.state.opt_state)
            if scale is not None:
                host["lr_plateau_scale"] = float(np.asarray(scale))
                host["lr"] *= host["lr_plateau_scale"]
        host.update(self.meter.percentiles())
        tput = self.meter.throughput(self.items_per_step)
        if tput is not None:
            unit = "images" if self.cfg.loss == "softmax_xent" else "tokens"
            host[f"{unit}_per_sec"] = tput
            host[f"{unit}_per_sec_per_chip"] = tput / jax.device_count()
            mfu = flops_lib.mfu_pct(host[f"{unit}_per_sec_per_chip"],
                                    self._flops_per_item, self._peak_flops)
            if mfu is not None:
                host["mfu_pct"] = round(mfu, 2)
                # perf plane gauge (obs/perf.py): the scrape-visible MFU
                # the capture attribution stamps into its journal record
                perf_lib.record_mfu(host["mfu_pct"])
        host["epoch"] = step // max(self.steps_per_epoch, 1)
        stats = getattr(self.train_loader, "stall_stats", None)
        if stats is not None:
            # Per-log-window input stall fraction: what % of the window the
            # consumer spent blocked on the host pipeline (SURVEY §7.4.1;
            # sustained-drill acceptance is < 5%).
            # Denominator = in-loop stepping time (meter.total_s), NOT
            # wall time between log calls: a window spanning an eval pass
            # or checkpoint wait would otherwise dilute the stall fraction
            # the sustained-drill <5% acceptance gates on.
            loop_s = self.meter.total_s
            prev = getattr(self, "_stall_prev", None)
            if prev is not None and loop_s > prev[1]:
                host["input_stall_pct"] = round(
                    100.0 * max(0.0, stats.wait_s - prev[0])
                    / (loop_s - prev[1]), 3)
                # input-stall regression detector (anomaly plane): one
                # observation per log window
                self.profiler.observe_stall_pct(host["input_stall_pct"],
                                                step)
            self._stall_prev = (stats.wait_s, loop_s)
        if self.cfg.obs.log_memory:
            host.update(device_memory_metrics())
        # Host/device memory telemetry (obs/memory.py): refresh the
        # OOM-headroom gauges at log cadence regardless of log_memory —
        # two /proc reads plus an already-cached jax stats call, and
        # they are the fleet plane's first alert-rule inputs.
        memory_lib.sample_memory_gauges()
        if self._sentinel_on or self.health is not None:
            scale = sentinel_numeric.cooldown_scale(self.state.opt_state)
            if scale is not None and scale != 1.0:
                # post-rewind cooldown: fold into the reported lr like
                # the plateau scale above (effective lr = schedule *
                # plateau * cooldown)
                host["lr_cooldown_scale"] = scale
                host["lr"] *= scale
        host["goodput_pct"] = self.goodput.snapshot()["goodput_pct"]
        if self.cfg.obs.straggler_metrics and jax.process_count() > 1:
            # Cross-host health gather (obs/cluster.py): every host
            # calls this symmetrically (the collective is inside), only
            # the logging below is rank-0. Fixed key schema — absent
            # backends contribute 0.0, never a missing key.
            hbm = device_memory_metrics().get("hbm_gb_in_use", 0.0)
            agg = cluster_lib.summarize({
                "step_time_p50": host.get("step_time_ms_p50", 0.0),
                "input_stall_pct": host.get("input_stall_pct", 0.0),
                "hbm_used": hbm,
            })
            host.update(agg)
            # Straggler blame trigger: every host computes the same
            # aggregate at the same step, so each fires the anomaly
            # locally and the capture windows align by construction.
            blamed = profiler_lib.straggler_blame(
                agg, self.cfg.obs.profile_straggler_ratio)
            if blamed is not None:
                self.profiler.anomaly(
                    "straggler", step, host=blamed,
                    p50_max=round(agg["step_time_p50_max"], 3),
                    p50_med=round(agg["step_time_p50_med"], 3))
        self.logger.log(step, host, prefix="train")
        return host

    def update_bn(self, num_batches: int = 50) -> None:
        """Re-estimate BN running statistics for the CURRENT eval params
        (the SWA/EMA mirror when averaging is on) — torch
        swa_utils.update_bn: averaged weights shift every layer's
        activation distribution, so the stats collected along the
        trajectory are wrong for them. Mechanism: a probe model with
        bn_momentum=0 makes one train-mode apply return exactly ONE
        batch's statistics; the cumulative average over ``num_batches``
        training batches (mean of batch means/vars — torch's
        momentum=None CMA computes the same) replaces state.batch_stats.
        No-op for stat-free models."""
        if not self.state.batch_stats:
            return
        if not any(f.name == "bn_momentum"
                   for f in dataclasses.fields(self.model)):
            return
        probe = dataclasses.replace(self.model, bn_momentum=0.0)
        params = self.state.eval_params

        @jax.jit
        def batch_stats_of(stats, batch):
            _, updated = probe.apply(
                {"params": params, "batch_stats": stats},
                *steps_lib.model_inputs(batch), train=True,
                mutable=["batch_stats"])
            return updated["batch_stats"]

        total = None
        n = 0
        for batch in self.train_epoch_fn(0):
            if self.liveness is not None:
                self.liveness.pulse()  # same non-step liveness as eval
            stats = batch_stats_of(self.state.batch_stats, batch)
            total = stats if total is None else jax.tree.map(
                jnp.add, total, stats)
            n += 1
            if n >= num_batches:
                break
        if n == 0:
            return
        avg = jax.tree.map(lambda t: t / n, total)
        self.state = self.state.replace(batch_stats=avg)
        if self.state.ema_batch_stats is not None:
            # eval reads the EMA stats mirror when one exists: the freshly
            # re-estimated stats (computed under eval_params) must land
            # there too or update_bn would be invisible to EMA eval.
            self.state = self.state.replace(ema_batch_stats=avg)
        self.recorder.record("update_bn", int(self.state.step), batches=n)

    def evaluate(self, step: int, prefix: str = "eval") -> dict:
        sums: dict[str, float] = {}
        n = 0
        stage_pre = perf_lib.get_input_stats().snapshot()
        with self.spans.span("train.eval", step=step):
            for batch in self.eval_epoch_fn(0):
                if self.liveness is not None:
                    # eval runs can dwarf hang_timeout_s; a healthy host
                    # mid-eval must not read as wedged to the monitor
                    self.liveness.pulse()
                m = self.eval_step(self.state, batch)
                for k, v in m.items():
                    sums[k] = sums.get(k, 0.0) + float(np.asarray(v))
                n += 1
        for k, v in perf_lib.get_input_stats().snapshot().items():
            self._eval_stage_s[k] += max(0.0, v - stage_pre.get(k, 0.0))
        if n == 0:
            return {}
        avg = {k: v / n for k, v in sums.items()}
        self.logger.log(step, avg, prefix=prefix)
        if self.best_ckpt is not None:
            if self.best_ckpt.update(
                    avg, self.state, step=step,
                    epoch=step // max(self.steps_per_epoch, 1)):
                self.recorder.record("ckpt_best", step,
                                     value=self.best_ckpt.best_value)
        self.meter.reset_clock()
        return avg

    def _maybe_inject_fault(self, step: int) -> None:
        """Step-boundary fault points (faults/registry.py): hard-kill
        (``step.crash`` — SURVEY §5.3c, no finally-save, no flush;
        exactly what a real host loss looks like to the launcher),
        transient straggle (``step.straggle``), and self-delivered
        preemption (``preempt.sigterm``). ``obs.fault_inject_at_step``
        arrives here too, shimmed to ``step.crash@step=N``."""
        self.faults.set_step(step)
        self.faults.maybe_fire("step.crash", step=step)
        # elastic.shrink: permanent host loss (rc 45, no finally-save).
        # Same mechanics as step.crash; the distinct point + rc lets a
        # shrink drill (docs/elastic.md, tools/chaos_soak.py --shrink)
        # schedule "this host never comes back" declaratively — under a
        # min_nnodes launcher the survivors re-rendezvous DEGRADED and
        # resume resharded.
        self.faults.maybe_fire("elastic.shrink", step=step)
        self.faults.maybe_fire("step.straggle", step=step)
        self.faults.maybe_fire("preempt.sigterm", step=step)
        # host.hang wedges HERE — after the step completed but BEFORE
        # this step's heartbeat/liveness beat, so both the local monitor
        # and the cross-host liveness plane see a step that never
        # finishes (sentinel/liveness.py drives the diagnosis).
        self.faults.maybe_fire("host.hang", step=step)

    def _maybe_inject_stall(self, step: int) -> None:
        """SURVEY §5.3a: wedge (don't crash) this step, first generation
        only — BEFORE the heartbeat beat, so the monitor sees a step that
        never completes (a hung host / wedged link, not a dead process) and
        must drive the dump→abort→gang-restart→resume chain itself."""
        import os

        stall = self.cfg.obs.stall_inject_at_step
        if (stall and step >= stall
                and os.environ.get("RESTART_GENERATION", "0") == "0"):
            print(f"[stall-inject] wedging at step {step}", flush=True)
            while True:  # only the heartbeat abort ends this
                time.sleep(60)

    # ------------------------------------------------------------- sentinel
    def _sentinel_observe(self, step: int, metrics: dict,
                          inflate_loss: bool = False) -> bool:
        """Host half of the numeric guard: classify the completed step as
        healthy / nonfinite / spiking, maintain the consecutive-bad
        streak, and return True when the streak says rewind. Reads the
        loss to host — a device sync per step, the cost the
        ``sentinel.enabled`` knob opts into (documented in config.py)."""
        import math

        loss = float(np.asarray(metrics["loss"]))
        gate_skipped = ("update_skipped" in metrics
                        and float(np.asarray(metrics["update_skipped"])) > 0)
        if inflate_loss:
            # Legacy hook: the step.loss_spike drill now corrupts
            # ``metrics["loss"]`` at the injection site in fit() (so
            # the log/scrape mirror sees the spike too); this flag
            # stays for callers staging their own observation.
            loss = loss * 1e6 if math.isfinite(loss) else loss
        reason = None
        if gate_skipped or not math.isfinite(loss):
            reason = "nonfinite"
            self._sentinel_skipped += 1
        elif self._spike.is_spike(loss):
            reason = "loss_spike"
        else:
            self._spike.add(loss)
            self._bad_streak = 0
        if reason is None:
            return False
        self._bad_streak += 1
        self.registry.counter(
            "sentinel_skipped_steps_total", labels={"reason": reason},
            help="train steps judged bad by the sentinel (nonfinite "
                 "update skipped in-graph, or loss spike flagged)").inc()
        self.registry.gauge(
            "sentinel_bad_streak",
            help="current consecutive bad-step count").set(self._bad_streak)
        print(f"[sentinel] step {step}: {reason} "
              f"(loss={loss:.6g}, streak "
              f"{self._bad_streak}/{self.cfg.sentinel.max_consecutive_bad})",
              flush=True)
        self.recorder.record("sentinel_bad_step", step, reason=reason)
        events_lib.emit("sentinel", "bad_step", step=step, reason=reason,
                        loss=loss, streak=self._bad_streak)
        if reason == "loss_spike":
            # anomaly hook: journal + (opt-in) open a capture window —
            # the profile of the steps AROUND a spike is the evidence
            # the post-mortem never has
            self.profiler.anomaly("loss_spike", step, loss=loss,
                                  streak=self._bad_streak)
        return self._bad_streak >= self.cfg.sentinel.max_consecutive_bad

    def _sentinel_rewind(self, step: int) -> int:
        """Restore the newest integrity-verified checkpoint, apply the
        LR cooldown, and hand the (possibly earlier) step counter back
        to the loop — which re-enters the epoch with the exact
        ``start_batch`` fast-forward. Returns the step to resume from
        (``step`` unchanged when there is nothing to rewind to)."""
        scfg = self.cfg.sentinel
        if self._rewinds >= scfg.max_rewinds:
            # Flag BEFORE raising: fit()'s finally must not force-save
            # the known-diverged live state over the rewind target.
            self._sentinel_aborted = True
            events_lib.emit("sentinel", "abort", step=step,
                            rewinds=self._rewinds)
            raise RuntimeError(
                f"[sentinel] rewind budget exhausted "
                f"({self._rewinds}/{scfg.max_rewinds}): training keeps "
                "diverging after repeated restore+cooldown — aborting "
                "rather than looping restore/diverge forever")
        self._bad_streak = 0
        if self._spike is not None:  # health-armed rewind, sentinel off
            self._spike.reset()
        if self.health is not None:
            # post-rewind: the pre-rewind telemetry regime may contain
            # the very divergence being recovered from
            self.health.reset()
        try:
            # a mid-flight async save must commit before we pick
            self.ckpt.wait()
        except OSError as e:
            # A terminal BACKGROUND persist failure (tiered plane)
            # re-raises at the next wait — here that history must not
            # abort the rewind: letting it unwind would reach fit()'s
            # finally with _sentinel_aborted unset and force-save the
            # known-diverged live state. The failed step's sealed hot
            # snapshot is still a valid rewind source, and the failure
            # was already printed and counted when it happened.
            print(f"[sentinel] ignoring earlier checkpoint persist "
                  f"failure during rewind ({type(e).__name__}: {e})",
                  flush=True)
        good = self.ckpt.latest_good_step()
        restored = (self.ckpt.restore(self.state, step=good)
                    if good is not None else None)
        if restored is None:
            print(f"[sentinel] step {step}: rewind wanted but no verified "
                  "checkpoint exists — resetting the detector and "
                  "continuing in place", flush=True)
            return step
        self.state, _meta = restored
        self.state = self.state.replace(
            opt_state=sentinel_numeric.scale_cooldown(
                self.state.opt_state, scfg.lr_cooldown_factor))
        self._rewinds += 1
        scale = sentinel_numeric.cooldown_scale(self.state.opt_state)
        self.registry.counter(
            "sentinel_rewinds_total",
            help="auto-rewinds to the last verified checkpoint after a "
                 "bad-step streak").inc()
        self.recorder.record("sentinel_rewind", step, to=good,
                             lr_scale=scale)
        events_lib.emit("sentinel", "rewind", step=step, to=int(good),
                        lr_scale=scale, rewind=self._rewinds)
        print(f"[sentinel] rewinding from step {step} to verified step "
              f"{good} (rewind {self._rewinds}/{scfg.max_rewinds}, "
              f"lr cooldown x{scfg.lr_cooldown_factor} -> total scale "
              f"{scale})", flush=True)
        return good

    def import_params(self, path: str) -> None:
        """Warm-start params from a (torch-layout) safetensors file
        (interop.py), keeping the configured sharding."""
        from pytorch_distributed_train_tpu.interop import (
            load_flax_safetensors,
        )

        host_params = load_flax_safetensors(path, self.state.params)
        # Place into the state's ACTUAL layout (state_sharding), not a
        # re-derivation from the rules — they differ under
        # mesh.zero_stage=1, where params are replicated over 'fsdp'.
        sharded = jax.device_put(host_params, self.state_sharding.params)
        self.state = self.state.replace(params=sharded)
        if self.state.ema_params is not None:
            # re-seed the EMA mirror too, else eval would run on the stale
            # random-init mirror until the EMA horizon washes it out
            self.state = self.state.replace(ema_params=sharded)
        if jax.process_index() == 0:
            print(f"[interop] warm-started params from {path}", flush=True)

    @property
    def preempted(self) -> bool:
        """Did a graceful SIGTERM preemption end fit() early? (train.py
        maps this to ``faults.preempt_exit_code``.)"""
        return self._preempted

    def close(self) -> None:
        self.heartbeat.stop()
        self.profiler.finish()
        # shared-memory decode pools (data/workers.py): stop worker
        # processes + release the rings (daemons would die with the
        # process anyway; tests build many Trainers per process)
        for loader in (getattr(self, "train_loader", None),
                       getattr(self, "eval_loader", None)):
            close = getattr(loader, "close", None)
            if close is not None:
                close()
        if self.liveness is not None:
            self.liveness.stop()
        self.ckpt.close()
        if self.best_ckpt is not None:
            self.best_ckpt.close()
        self.logger.close()
        if self.metrics_server is not None:
            from pytorch_distributed_train_tpu.obs import exposition

            # compare-and-clear: a newer Trainer's trigger (several
            # Trainers per test process) must survive this close
            exposition.clear_profile_trigger(self._profile_trigger)
            self.metrics_server.close()
            self.metrics_server = None


def _poison_batch_nan(batch: dict) -> dict:
    """``step.nan`` drill: overwrite every float-dtype batch field with
    NaN — the loss and grads of the next step go non-finite exactly the
    way a corrupted record or overflowed activation would make them, and
    the in-graph guard must absorb it. Elementwise op on the sharded
    arrays: layout preserved, no host round-trip. Integer-only batches
    (token ids with no mask/teacher field) have nothing to poison; the
    drill warns instead of silently passing."""
    out = {}
    poisoned = False
    for k, v in batch.items():
        if jnp.issubdtype(v.dtype, jnp.floating):
            out[k] = v * jnp.asarray(jnp.nan, dtype=v.dtype)
            poisoned = True
        else:
            out[k] = v
    if not poisoned:
        print("[fault-inject] step.nan: no float field in the batch to "
              "poison (integer-only inputs) — step left healthy",
              flush=True)
    return out


def device_memory_metrics() -> dict:
    """HBM usage of local device 0, or {} where the backend reports none
    (CPU). Keys mirror the reference's torch.cuda.memory_allocated /
    max_memory_allocated logging convention."""
    stats = jax.local_devices()[0].memory_stats()
    if not stats:
        return {}
    out = {}
    if "bytes_in_use" in stats:
        out["hbm_gb_in_use"] = stats["bytes_in_use"] / 2**30
    peak = stats.get("peak_bytes_in_use")
    if peak is not None:
        out["hbm_gb_peak"] = peak / 2**30
    return out
