"""Config system: one dataclass tree + named presets + CLI overrides.

Replaces the reference harness's argparse CLI + launcher env contract
(SURVEY.md §5.6; BASELINE.json:5 "behind the same config ... interface").
The five BASELINE.json configs (lines 7-11) ship as named presets — they are
the acceptance matrix:

    resnet18_cifar10   ResNet-18 / CIFAR-10, single process       (line 7)
    resnet50_imagenet  ResNet-50 / ImageNet, data-parallel        (line 8)
    vit_b16_imagenet   ViT-B/16, bf16 + grad accumulation         (line 9)
    bert_base_mlm      BERT-base MLM, LAMB optimizer              (line 10)
    llama2_7b          Llama-2 7B pretrain, GSPMD param sharding  (line 11)

Parallelism is *config*, not code: the ``mesh`` section chooses axis sizes on
``('data','fsdp','tensor','context')`` and the partition rules in
parallel/partition.py do the rest (SURVEY.md §7.2).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any


def _fields(cls) -> dict[str, dataclasses.Field]:
    return {f.name: f for f in dataclasses.fields(cls)}


@dataclass
class ModelConfig:
    """Which model to build and its architecture knobs.

    ``name`` keys into models/registry.py. Transformer fields are ignored by
    the vision models and vice versa.
    """

    name: str = "resnet18"
    num_classes: int = 10
    image_size: int = 32
    # ResNet ImageNet stem: "conv" (7x7/s2, torch-identical) or
    # "space_to_depth" (mathematically-exact 4x4/s1 rewrite over a 2x2
    # space-to-depth input — MXU-friendly C_in 3→12; the parameter keeps
    # the canonical (7,7,3,F) layout so checkpoints/interop are unchanged)
    stem: str = "conv"
    # ViT
    patch_size: int = 16
    # Transformer family (ViT / BERT / Llama)
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 0  # 0 → = num_heads (MHA); <num_heads → GQA (Llama)
    mlp_dim: int = 3072
    vocab_size: int = 30522
    max_seq_len: int = 512
    dropout_rate: float = 0.0
    # Llama
    rope_theta: float = 10000.0
    # Linear RoPE position interpolation (HF rope_scaling "linear"): >1
    # stretches the usable context to rope_scaling x the pretrain length
    # (set max_seq_len accordingly; positions divide by the factor).
    rope_scaling: float = 1.0
    # "linear" (positions divide by the factor; fine-tune for quality) or
    # "ntk" (base rescales, high frequencies preserved; often works
    # zero-shot) — models/llama.py rope_frequencies
    rope_scaling_type: str = "linear"
    rms_norm_eps: float = 1e-5
    # T5 family (models/t5.py): decoder stack depth (0 → = num_layers) and
    # the bucketed relative-position-bias geometry.
    decoder_layers: int = 0
    rel_pos_buckets: int = 32
    rel_pos_max_distance: int = 128
    # Tie the LM head to the shared embedding (t5: published v1.0
    # checkpoints tie + rescale decoder output by d_model**-0.5; set true
    # to load them via interop).
    tie_word_embeddings: bool = False
    # Memory: rematerialise each transformer block's activations in backward
    remat: bool = False
    # What remat may keep resident (models/remat.py — the selective
    # activation-checkpointing dial): "full" recomputes everything,
    # "dots" keeps matmul outputs (XLA dots_saveable), "dots_no_batch"
    # keeps only non-batch-dim matmuls.
    remat_policy: str = "full"
    # Fused chunked LM-head loss (llama/gpt2): head matmul + CE computed per
    # sequence chunk under remat so (B,S,V) logits never materialize
    # (losses.chunked_causal_ce). Requires loss="fused_causal_lm_xent".
    fused_lm_loss: bool = False
    # Attention backend for this process: auto (pallas on TPU when
    # supported+profitable, else XLA), or force xla / pallas / chunked
    # (pure-XLA flash-style query-chunked path — O(S*chunk) memory,
    # compiles on backends that can't take Mosaic kernels). The
    # PDTT_ATTENTION_IMPL env var overrides (ops/attention.py).
    attention_impl: str = "auto"
    # Sliding-window attention span in tokens (Mistral recipe): each query
    # attends to its trailing `attention_window` keys. 0 = full causal.
    # Llama family; composes with every backend: xla/chunked mask or
    # band-slice, pallas masks within tiles and skips out-of-band blocks,
    # ring attention skips out-of-band hops, ulysses windows its full-seq
    # local core.
    attention_window: int = 0
    # KV-cache STORAGE dtype for decode/serving ("" = compute dtype).
    # "float8_e4m3fn" halves cache HBM and the per-step cache read —
    # decode's bandwidth bill (the fp8-KV recipe of production servers);
    # llama + gpt2 families. Training attention is untouched.
    kv_cache_dtype: str = ""
    # Packed-block document isolation (llama/gpt2 training): >= 0 names
    # the EOS id that delimits documents inside packed seq_len blocks
    # (data/text.py packing). Attention is then masked across documents
    # and rope/wpe positions restart at 0 per document — each doc trains
    # exactly as if unpacked. -1 = off (simple packing: docs see their
    # pack-mates' tails; the GPT-2/llama-pretrain default).
    segment_eos_id: int = -1
    # Pipeline parallelism (model name "llama_pp"; SURVEY §2.3 PP row):
    # microbatch count (0 → = stage count), schedule ("gpipe" | "1f1b" |
    # "interleaved"), and chunks per device for the interleaved schedule.
    pipeline_microbatches: int = 0
    pipeline_schedule: str = "gpipe"
    pipeline_chunks: int = 2
    # Mixture-of-Experts (SURVEY §2.3 EP row; ops/moe.py). num_experts>1
    # swaps the dense MLP for top-k routed experts on every moe_every-th
    # block; expert params shard over the 'expert' mesh axis.
    num_experts: int = 0
    expert_top_k: int = 2
    expert_capacity_factor: float = 1.25
    moe_every: int = 1
    moe_aux_weight: float = 0.01
    # Router style: "topk" (GShard/Switch — tokens choose) or
    # "expert_choice" (experts choose their top-capacity tokens: perfect
    # load balance structurally, no balance loss; a token may be served
    # by 0..E experts). Caveat for causal LMs: expert-choice selection
    # ranks over the whole batch, so training is mildly non-causal
    # (ops/moe.py::expert_choice_dispatch docstring).
    moe_router: str = "topk"
    # expert_choice ranks tokens over the whole flattened batch, so a
    # causal-LM loss trained with it leaks future positions into routing.
    # The trainer refuses that combination unless this is set — an explicit
    # "I understand the Zhou et al. caveat" opt-in.
    moe_router_allow_noncausal: bool = False
    moe_zloss_weight: float = 1e-3
    # Fused elementwise block epilogues (ops/fused_update.py; vit/bert):
    # the bias+GELU MLP epilogue and (post-LN bert) the residual-add+
    # LayerNorm epilogue compute as single tagged expressions XLA keeps
    # in one elementwise kernel, and the tag ("fused_epilogue",
    # jax.ad_checkpoint.checkpoint_name) gives remat a handle — policy
    # "no_fused_epilogue" (models/remat.py) recomputes exactly these
    # cheap chains in backward instead of saving them. Param tree and
    # numerics are unchanged (same names, same math, same fp32 norms);
    # the knob exists so the A/B is one config flip.
    fused_epilogues: bool = False
    # AQT-style int8 quantized TRAINING ("" | "int8"; llama/llama_pp/gpt2):
    # attention + MLP matmuls run int8×int8→int32 on the MXU (2× bf16
    # MACs/cycle on v5e) with dynamic symmetric absmax scales and a
    # straight-through backward — quant.int8_dot_general. lm_head and MoE
    # experts stay in the compute dtype. Decode-side weight-only int8 is
    # separate (generate/bench --quantize int8).
    quant_training: str = ""


@dataclass
class DataConfig:
    """Input pipeline. ``batch_size`` is GLOBAL (summed over all hosts/chips),
    matching the reference's per-step effective batch under DDP."""

    # synthetic_images | cifar10 | imagenet_folder | synthetic_lm |
    # text_lm (real corpus) | text_mlm (real corpus when text_files set,
    # else synthetic masking stream)
    dataset: str = "synthetic_images"
    data_dir: str = ""
    # Host loader backend (SURVEY C17): "threads" (in-process pool) or
    # "grain" (Grain worker PROCESSES — the torch-DataLoader-worker model)
    loader: str = "threads"
    batch_size: int = 128
    eval_batch_size: int = 0  # 0 → = batch_size
    num_workers: int = 4
    prefetch: int = 2  # device-side double/triple buffer depth
    shuffle: bool = True
    drop_last: bool = True  # SPMD needs static shapes; pad-or-drop final batch
    seed: int = 0
    # "" | "inverse_class" — torch WeightedRandomSampler recipe: train-time
    # draws WITH replacement ∝ 1/class-frequency (array datasets w/ labels)
    weighted_sampling: str = ""
    # Elastic resharding (docs/elastic.md): shard the input stream by the
    # LAUNCHER world (NUM_PROCESSES / PROCESS_ID — elastic.elastic_world)
    # instead of the jax process world. For tpurun gangs whose workers
    # are single-process jax runtimes (the CPU drills; one-runtime-per-
    # host deployments): a degraded generation then recomputes per-host
    # shards from the SHRUNKEN world mid-epoch — the global batch stays
    # fixed, per-host batch rescales, and the union of all hosts' batch
    # b is the same global index set at any world size.
    elastic_shards: bool = False
    # Batch augmentation (device-side, ops/mixup.py — the torchvision/timm
    # --mixup-alpha/--cutmix-alpha recipe knobs); 0.0 disables.
    mixup_alpha: float = 0.0
    cutmix_alpha: float = 0.0
    mixup_switch_prob: float = 0.5
    # Native libjpeg batch decode for imagenet_tar (native/jpegdec.cpp):
    # decode + crop-resize + normalize in C++ threads instead of per-item
    # PIL. Falls back silently when the lib can't build, shards hold PNGs,
    # or RandAugment is on (PIL-op chain). Same crop policy, plain-bilinear
    # resampling (PIL filters on downscale — statistically equivalent).
    native_decode: bool = False
    # Shared-memory multi-process decode plane (data/workers.py): >0
    # runs decode/augment in N forked worker processes writing decoded
    # batches into preallocated shared-memory ring slots (no pixel
    # pickling), fronting BOTH loaders. 0 = in-process (threads for the
    # "threads" loader, grain's own machinery for "grain"). Clamped to
    # cpu_count-1 (workers.pool_budget); batch composition and resume
    # semantics are byte-identical to the in-process path.
    mp_workers: int = 0
    # Ring depth for the shared-memory pool (0 -> mp_workers + 2).
    mp_slots: int = 0
    # Packed pre-decoded sample cache (data/packed_cache.py): directory
    # of fixed-record u8 shards built by tools/pack_dataset.py. When set
    # on an image dataset, a valid cache for the split replaces the
    # decode path with one mmap'd strided read (hit/miss counted in the
    # registry); absent/invalid caches fall through to the original
    # dataset. Dataset name "packed_images" reads shards directly from
    # data_dir (dir or glob).
    packed_cache_dir: str = ""
    # Verify shard CRCs at open (full payload read; tools and tests —
    # training opens skip it and rely on the pack-time CRC).
    packed_verify: bool = False
    # Device-side augmentation (ops/device_augment.py): datasets that
    # can ship raw uint8 pixels skip host-side crop/flip/RandAugment/
    # normalize; the jitted train step applies them on-device under the
    # same PRNG-folding discipline as dropout. Host path is unchanged
    # when off; datasets that cannot ship u8 (synthetic/LM/native-decode
    # tar) ignore the flag.
    device_augment: bool = False
    # Host-side RandAugment (data/augment.py; ImageFolder train path).
    # num_ops 0 disables; magnitude in [0, 30] (torchvision's 31 bins).
    # With device_augment on, the RandAugment op space moves on-device
    # (photometric/affine u8 ops — ops/device_augment.py documents the
    # semantic deltas vs the PIL chain).
    randaugment_num_ops: int = 0
    randaugment_magnitude: int = 9
    # LM datasets
    seq_len: int = 512
    # Decoder-side target length for seq2seq datasets (0 → = seq_len).
    tgt_seq_len: int = 0
    mlm_prob: float = 0.15
    # Real-text corpus (datasets text_lm / text_mlm, data/text.py): glob of
    # local .txt/.jsonl files, and an optional local HF-tokenizer directory
    # (absent → built-in byte-level tokenizer, vocab 259).
    text_files: str = ""
    tokenizer_path: str = ""
    # text_files matching one .bin selects the memory-mapped pre-tokenized
    # stream (nanoGPT-style flat token file); this is its element dtype.
    token_bin_dtype: str = "uint16"
    # Synthetic dataset length (steps worth of fake data per epoch)
    synthetic_size: int = 51200


@dataclass
class OptimConfig:
    """Optimizer + LR schedule (reference: torch.optim.SGD / LAMB — SURVEY C20)."""

    # sgd | momentum | adamw | lamb | adam | lars | adafactor | muon
    name: str = "sgd"
    learning_rate: float = 0.1
    warmup_steps: int = 0
    # constant | cosine | step | linear | polynomial | onecycle |
    # cosine_restarts
    schedule: str = "cosine"
    poly_power: float = 1.0  # polynomial schedule exponent (1.0 = linear)
    # onecycle: fraction of the horizon spent ramping up (torch OneCycleLR
    # pct_start); cosine_restarts: first cycle length in optimizer updates
    # (0 → horizon/4) and per-restart length multiplier (torch T_0/T_mult).
    onecycle_pct_start: float = 0.3
    restart_period: int = 0
    restart_mult: float = 1.0
    # step schedule
    step_decay_rate: float = 0.1
    step_decay_every: int = 30  # epochs
    momentum: float = 0.9
    nesterov: bool = False
    weight_decay: float = 1e-4
    # No-decay param groups (the torch-recipe `no_decay=['bias','LayerNorm']`
    # pattern): comma-separated regexes matched against the '/'-joined param
    # path; matching params skip weight decay (and LARS trust-ratio scaling).
    # Flax naming: biases are 'bias', Layer/RMS/BatchNorm scales are 'scale'.
    decay_exclude: str = ""
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    grad_clip_norm: float = 0.0  # 0 → off
    # ReduceLROnPlateau analogue (optax.contrib.reduce_on_plateau), driven
    # by the per-step training loss inside the jitted step (torch drives
    # it with whatever metric you pass — commonly val loss per epoch; here
    # the signal is the train loss, smoothed over plateau_accumulation
    # updates). plateau_factor > 0 enables; patience/cooldown count
    # optimizer updates.
    plateau_factor: float = 0.0
    plateau_patience: int = 10
    plateau_cooldown: int = 0
    plateau_accumulation: int = 1
    plateau_min_scale: float = 0.0
    # Keep optimizer state (adam/lamb moments, momentum) in pinned HOST
    # memory between steps — the ZeRO-Offload analogue, via JAX memory
    # kinds. Frees ~2 params-worth of HBM for adam-family optimizers at the
    # cost of host<->HBM transfers XLA overlaps with compute. TPU-only
    # (the CPU test backend cannot execute the placement custom-call).
    offload_state: bool = False
    # Storage dtype for optimizer moment/momentum accumulators ("" → fp32).
    # "bfloat16" halves first-moment HBM for adam/adamw/lamb (and the SGD
    # momentum buffer) — the update math stays fp32, only storage narrows.
    # Second moments (nu) always stay fp32: bf16's 8-bit mantissa loses the
    # small squared-gradient increments that drive the Adam denominator.
    moment_dtype: str = ""
    # adafactor: factor second moments above this dim (optax default 128);
    # momentum is a SEPARATE knob (0 → stateless, the paper default) so the
    # SGD-oriented `momentum=0.9` default can't silently re-add the
    # first-moment buffer adafactor exists to avoid.
    adafactor_min_dim_factored: int = 128
    adafactor_momentum: float = 0.0
    # muon: momentum coefficient for the orthogonalized branch (matrix
    # params); beta1/beta2 configure its adam branch (everything else).
    muon_beta: float = 0.95
    # Layer-wise LR decay (timm/BEiT fine-tune recipe): depth-d params'
    # updates scale by decay^(max_depth - d); 1.0 → off. Head/final norm
    # keep full LR, embeddings/stem train slowest.
    layer_lr_decay: float = 1.0
    accum_steps: int = 1  # optax.MultiSteps microbatching (≡ DDP no_sync)
    # Polyak/EMA weight averaging (torch-recipe "model EMA"): decay per
    # step, 0 → off. Eval runs on the EMA mirror when enabled.
    ema_decay: float = 0.0
    # Stochastic Weight Averaging (torch.optim.swa_utils): from the
    # swa_start_step-th OPTIMIZER UPDATE on (denominated like
    # warmup_steps — under accum_steps one update spans accum micro-
    # steps), the mirror keeps the EQUAL-WEIGHT running mean of params
    # sampled every swa_every updates; eval runs on it (same mirror as
    # EMA — the two are mutually exclusive). Like torch's AveragedModel,
    # BN stats are NOT re-estimated automatically (torch needs an
    # explicit update_bn pass too).
    swa_start_step: int = 0  # 0 → off
    swa_every: int = 1
    # SWALR: constant LR once SWA collection starts (0 → keep the base
    # schedule running)
    swa_lr: float = 0.0
    # torch swa_utils.update_bn analogue: after training, re-estimate BN
    # statistics for the AVERAGED weights over this many training
    # batches (averaged weights + stale stats is the classic SWA
    # mistake). 0 → off; no-op for BN-free models. Runs before the
    # final evaluation when SWA/EMA is on.
    swa_update_bn_batches: int = 0
    # Grad-compression hook (SURVEY C8 ddp_comm_hooks equivalent):
    # "none" | "bf16" | "fp16" | "powersgd" (grad_hooks.py)
    grad_hook: str = "none"
    powersgd_rank: int = 2
    # Final LR fraction for cosine
    end_lr_factor: float = 0.0


@dataclass
class PrecisionConfig:
    """Mixed precision policy. Replaces autocast + GradScaler (SURVEY C18/C19):
    params stay fp32, compute runs in ``compute_dtype``. bf16 needs no loss
    scaling on TPU; ``loss_scale`` keeps the reference's GradScaler knob for
    fp16 experiments (default off)."""

    compute_dtype: str = "float32"  # float32 | bfloat16
    param_dtype: str = "float32"
    # "none" | "dynamic" | a float for static scaling
    loss_scale: str = "none"
    loss_scale_init: float = 2.0**15
    loss_scale_growth_interval: int = 2000


@dataclass
class MeshConfig:
    """Device mesh axis sizes. -1 on one axis → fill with remaining devices.

    stage   — pipeline parallelism (GPipe/1F1B microbatch schedules)
    data    — batch sharding (DP; reference DDP, SURVEY §2.3)
    fsdp    — parameter sharding (ZeRO/FSDP → GSPMD, BASELINE.json:11)
    expert  — MoE expert parallelism (token all-to-all dispatch)
    tensor  — megatron TP on heads / mlp hidden
    context — sequence/ring-attention parallelism (SURVEY §5.7)
    """

    stage: int = 1
    data: int = -1
    fsdp: int = 1
    expert: int = 1
    tensor: int = 1
    context: int = 1
    # Which mesh axes batch is sharded over (data+fsdp is the common combo).
    batch_axes: tuple[str, ...] = ("data", "fsdp")
    # ZeRO stage on the 'fsdp' axis (torch FSDP ShardingStrategy analogue,
    # steps.state_shardings): 3 = params+optimizer sharded (FULL_SHARD,
    # default); 1 = optimizer-state-only sharding, params replicated
    # (fits when weights fit per-chip but adam moments don't).
    zero_stage: int = 3
    # Attention algorithm when context > 1 (SURVEY §5.7):
    #   ring    — lax.ppermute KV rotation around the ICI ring; any size
    #   ulysses — all-to-all head↔seq swap; needs heads % context == 0
    context_impl: str = "ring"
    # Ring sequence layout: "zigzag" gives each device chunks (i, 2n−1−i)
    # so causal-triangle work balances across the ring
    # (ops/ring_attention.py::zigzag_perm). Exact at any size; costs one
    # gather each way per attention call. The ~2× causal saving is
    # realized by the pallas chunk backend's block skipping, which needs
    # the half-chunk to cover ≥1 KV block: S_local/2 ≥ block_k (i.e.
    # seq/ring ≥ 2048 at the default 1024-wide blocks) — exactly the
    # long-context regime CP exists for. Below that (or on the einsum
    # backend) zigzag is correct but pays the gathers for no win.
    # Ignored by ulysses / non-causal attention.
    context_layout: str = "contiguous"
    # Megatron-style sequence parallelism (SURVEY §2.3 SP row): with
    # tensor>1, shard activations along sequence over the 'tensor' axis
    # between TP matmuls (norms/residuals run seq-sharded; GSPMD inserts
    # the all-gather/reduce-scatter pair at the matmul boundaries).
    sequence_parallel: bool = False


# XLA flag preset for the overlapped-collectives path (steps.py
# re-exports; bench.py/train.py apply it to XLA_FLAGS before the first
# jax import): the latency-hiding scheduler + async collective fusion
# are what let the per-bucket in-scan reductions actually overlap the
# next microbatch's compute instead of serializing after it. Defined
# here (jax-free module) so host-side entrypoints can set the env
# without importing a backend.
LATENCY_HIDING_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true "
    "--xla_tpu_enable_async_collective_fusion=true "
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true "
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true "
    "--xla_tpu_overlap_compute_collective_tc=true"
)


def ensure_latency_hiding_flags(env=None) -> bool:
    """Append the scheduler preset to XLA_FLAGS unless already present.
    Returns True when the env was modified. Only effective if called
    before the first jax backend initialization — which is why it lives
    HERE (jax-free module) and not in steps.py: entrypoints import this
    before any backend-registering import. TPU backends only — XLA:CPU
    rejects unknown ``--xla_tpu_*`` flags fatally, so callers gate on
    the resolved platform (see bench.py)."""
    import os

    env = env if env is not None else os.environ
    flags = env.get("XLA_FLAGS", "")
    if "--xla_tpu_enable_latency_hiding_scheduler" in flags:
        return False
    env["XLA_FLAGS"] = (flags + " " + LATENCY_HIDING_XLA_FLAGS).strip()
    return True


@dataclass
class TrainStepConfig:
    """Compute-graph optimization layer for the train step (steps.py +
    ops/fused_update.py; docs/performance.md "Compute side"). All knobs
    default off — the single-shot GSPMD step is the reference program
    and every knob here is measured against it."""

    # Microbatched train step: lax.scan over N microbatches inside ONE
    # donated step executable, grads accumulated in the carry and the
    # (clip → update → gate) epilogue applied once on the accumulated
    # mean — the activation-memory/overlap twin of optim.accum_steps
    # (optax.MultiSteps), which instead runs N separate host-driven
    # micro-steps. 1 = off. Mutually exclusive with optim.accum_steps>1
    # (both would compound). The global batch must divide by it; LR
    # schedules count optimizer steps as before (one scan = one step).
    grad_accum_steps: int = 1
    # Overlapped gradient collectives (the DDP-reducer analogue, SURVEY
    # C7/[TORCH] reducer.hpp:285): run the step under shard_map over the
    # batch axes and issue per-BUCKET grad reductions inside the
    # accumulation scan — microbatch i's collectives overlap microbatch
    # i+1's compute under XLA's latency-hiding scheduler
    # (steps.LATENCY_HIDING_XLA_FLAGS). Requires params/opt state
    # replicated over the batch axes (pure DP or mesh.zero_stage=1
    # layouts); refused loudly otherwise.
    overlap_collectives: bool = False
    # Bucket size for the per-bucket reductions, mirroring DDP's
    # bucket_cap_mb=25 default; buckets fill in REVERSE parameter order
    # (the order backward produces grads — reducer semantics).
    grad_bucket_mb: int = 25
    # Fused optimizer epilogue (ops/fused_update.py): clip-by-global-
    # norm + optimizer update + non-finite gate computed in ONE pass
    # over the grad tree (per-leaf select against the old state) instead
    # of the chain's three passes plus the gate's whole-tree two-branch
    # select. Numerically identical to the optax chain — which remains
    # the reference oracle (tests pin fused == chain bit-for-bit,
    # LR-cooldown leaf included); configs the fast path cannot express
    # (plateau, layer_lr_decay, grad hooks, exotic optimizers) are
    # refused loudly rather than silently falling back.
    fused_epilogue: bool = False


@dataclass
class CheckpointConfig:
    """Orbax-backed checkpointing (SURVEY §5.4). ``resume='auto'`` restores the
    latest step if present — the default path, not a flag (SURVEY §5.3b)."""

    dir: str = "checkpoints"
    save_every_steps: int = 1000
    max_to_keep: int = 3
    resume: str = "auto"  # auto | none | <explicit path>
    async_save: bool = True
    # Track the best eval checkpoint (the torch-recipe `model_best.pth`
    # pattern): "" → off; else an eval-metric name ("accuracy", "loss", …).
    # When the metric improves, the state is saved under <dir>/best
    # (max_to_keep=1); resume still uses the latest cadence checkpoint.
    best_metric: str = ""
    best_mode: str = "max"  # max | min
    # Per-step integrity manifests (faults/integrity.py): after each
    # Orbax commit, inventory the step's files (sizes + content hashes)
    # under <dir>/manifests/; restore verifies and falls back past
    # corrupt/partial steps to the newest verified one.
    integrity: bool = True
    # ---- tiered async checkpointing plane (ckpt/; docs/checkpointing.md)
    # tiered=true replaces the plain Orbax manager with the tiered one:
    # at a save boundary the step loop blocks only for the device->host
    # snapshot copy (ckpt_blocking_ms); a background persister thread
    # runs seal -> local-disk spill -> peer publish -> Orbax write +
    # manifest (ckpt_persist_ms), with at most ONE persist in flight
    # (an early next boundary waits — the ckpt.drain goodput bucket).
    # Restores (sentinel rewind, elastic resume) try RAM -> local disk
    # -> peer store -> Orbax, each tier verified.
    tiered: bool = False
    # Hot retention: keep the newest hot_keep sealed snapshots per tier
    # (RAM and local disk age under the same policy), plus every step
    # divisible by keep_every (0 = off). The newest manifest-verified
    # persistent step and the newest sealed hot step are always pinned.
    hot_keep: int = 2
    keep_every: int = 0
    # Local-disk spill tier: per-host sealed-snapshot copies that
    # survive a process kill (same-host elastic restart restores in ms).
    # Root dir "" -> <dir>/hot (each host appends host_<n>) — a
    # single-host convenience. On a multi-host deployment whose <dir>
    # is shared/network storage, point hot_dir at NODE-LOCAL scratch
    # (/tmp, local SSD): spilling to the same shared FS Orbax writes
    # would double persistent-storage traffic and forfeit the
    # fast-local-restart property the tier exists for.
    hot_disk: bool = True
    hot_dir: str = ""
    # Cross-host peer exchange over the launcher's KV store: each host
    # publishes its newest sealed snapshot (<= peer_publish_max_bytes;
    # larger models skip publication and keep disk+Orbax tiers) and a
    # restoring worker fetches it before touching persistent storage.
    peer_fetch: bool = True
    peer_publish_max_bytes: int = 64 * 1024 * 1024


@dataclass
class ObsConfig:
    """Observability: metrics cadence, profiler window, failure detection
    (SURVEY §5.1-5.5)."""

    log_every_steps: int = 50
    jsonl_path: str = ""  # "" → <ckpt dir>/metrics.jsonl
    tensorboard: bool = False
    # Legacy fixed profiler window — now a shim over the managed
    # profiler plane (obs/profiler.py): profile_num_steps > 0 pre-queues
    # ONE capture at profile_start_step writing into profile_dir's root
    # (old output layout, exempt from the capture ring).
    profile_start_step: int = 0  # 0 → profiling off
    profile_num_steps: int = 0
    profile_dir: str = "profiles"
    # ---- event journal (obs/events.py; docs/observability.md schema).
    # Append-only per-host JSONL of structured run events (faults,
    # sentinel verdicts, ckpt traffic, restarts, captures) merged by
    # tools/timeline_report.py. "" dir → <checkpoint.dir>/events; the
    # PDTT_EVENTS_DIR env var (tpurun --events-dir) overrides "".
    events: bool = True
    events_dir: str = ""
    # ---- distributed request tracing (obs/tracing.py): trace spill
    # directory for the tail-based sampler ("" → <checkpoint.dir>/traces,
    # beside the event journal; PDTT_TRACE_DIR overrides ""), the random
    # baseline retention percentage, and the slow-trace retention
    # threshold. Trainer spans carry (gen, step) correlation tags so a
    # serving tail on a co-resident host lines up against training.
    trace_dir: str = ""
    trace_sample_pct: float = 0.0
    trace_keep_slow_ms: float = 250.0
    # ---- managed profiler plane (obs/profiler.py): bounded N-step
    # jax.profiler windows with an artifact ring, triggered on cadence,
    # on demand (trigger file / POST /profile; store-coordinated under
    # tpurun so all hosts capture the same steps), and by anomaly hooks.
    profile_window_steps: int = 5   # steps per managed capture
    profile_every_steps: int = 0    # cadence trigger (0 = off)
    profile_ring: int = 4           # completed capture dirs retained
    profile_trigger_file: str = ""  # "" → <checkpoint.dir>/PROFILE
    # Anomaly auto-capture (sentinel loss-spike, straggler blame, the
    # step-time/input-stall regression detectors). Off by default: an
    # unattended jax.profiler session is a real side effect (CPU+disk)
    # the operator opts into; anomaly EVENTS are journaled regardless.
    profile_on_anomaly: bool = False
    profile_cooldown_steps: int = 200  # min steps between auto-captures
    # Rolling median+MAD regression detectors (sentinel/numeric.py
    # SpikeDetector pointed at wall-clock health): step time per step,
    # input-stall % per log window.
    profile_regress_window: int = 64
    profile_regress_sigma: float = 8.0
    profile_regress_min_samples: int = 16
    profile_regress_min_rel: float = 0.5
    profile_stall_min_pct: float = 5.0  # abs floor for stall anomalies
    # Straggler blame trigger: cluster max step-time p50 >= ratio x the
    # median (needs obs.straggler_metrics + multi-host). 0 = off.
    profile_straggler_ratio: float = 2.0
    profile_top_ops: int = 5        # rows in the journaled xplane summary
    # ---- perf ledger (obs/perf.py; docs/performance.md): rank 0
    # appends one throughput/MFU/stall-split row per fit() to an
    # append-only JSONL the regression gate (tools/perf_ledger --check)
    # compares across runs. "" path → <checkpoint.dir>/perf_ledger.jsonl
    # (the PDTT_PERF_LEDGER env var overrides "").
    perf_ledger: bool = True
    perf_ledger_path: str = ""
    heartbeat_timeout_s: float = 0.0  # 0 → heartbeat monitor off
    debug_nans: bool = False
    # Cross-host input-divergence check cadence (0 → off); SURVEY §5.2
    check_input_sync_every: int = 0
    # Fault injection (SURVEY §5.3c): hard-kill this process when the step
    # counter reaches this value — but only in restart generation 0, so a
    # tpurun-supervised job crashes exactly once and must recover through
    # checkpoint resume. 0 → off. Test hook; no effect on saved state.
    # DEPRECATED: kept as a back-compat shim routed through the fault
    # registry as ``step.crash@step=N`` — new scenarios should use
    # ``faults.inject`` (docs/fault_tolerance.md), which composes
    # multiple faults per run.
    fault_inject_at_step: int = 0
    # Stall injection (SURVEY §5.3a): WEDGE this process (sleep forever,
    # heartbeat never beats) when the step counter reaches this value —
    # generation 0 only, like fault_inject_at_step. Exercises the full
    # stalled-step chain: heartbeat fires → flight-recorder dump → abort
    # (exit 134) → gang restart → checkpoint resume. 0 → off. Test hook.
    stall_inject_at_step: int = 0
    # Log device memory (HBM bytes_in_use / peak) with train metrics.
    # No-op on backends that don't report memory_stats (CPU).
    log_memory: bool = False
    # Live Prometheus exposition sidecar (obs/exposition.py): 0 = off
    # (default — a port bind is a side effect), >0 = bind that port,
    # -1 = ephemeral OS-assigned port (tests / several trainers per
    # host; read it back from Trainer.metrics_server.port). Serves
    # GET /metrics (text format v0.0.4) and /healthz. A fixed port
    # already bound by another local worker falls back to an ephemeral
    # one (logged once); under tpurun the ACTUAL bound port is
    # published to the launcher store as an obs endpoint record, so
    # the fleet collector (obs/collector.py) scrapes the right port
    # either way.
    metrics_port: int = 0
    # Chrome trace.json of host spans (obs/spans.py), written by process
    # 0 when fit() ends ("" → <checkpoint.dir>/trace.json). Load in
    # chrome://tracing or Perfetto next to the xplane device trace.
    trace_path: str = ""
    # Cross-host straggler aggregation (obs/cluster.py): at log cadence
    # every host contributes {step_time_p50, input_stall_pct, hbm_used}
    # via process_allgather; rank-0 logs cluster min/med/max plus the
    # arg-max host id. Only adds log keys when process_count > 1; the
    # collective runs off the step path (log cadence, consumer thread).
    straggler_metrics: bool = True
    # Per-top-level-module grad norms in the train metrics
    # (grad_norm/<module> keys) — which block explodes/vanishes.
    log_module_grad_norms: bool = False
    # Model-health observability plane (obs/model_health.py;
    # docs/observability.md "Model health"): the in-graph training-
    # dynamics pass (per-module grad/param/update norms + update-to-
    # param ratios, ops/model_health.py) in the step metrics, plus the
    # host-side monitor that journals divergence early-warnings under
    # the ``model`` event category and can arm the sentinel rewind /
    # profiler hooks BEFORE the loss diverges. Bitwise no-op on the
    # update path when off.
    model_health: bool = False
    # Persistent XLA compilation cache dir ("" → leave jax's default): cuts
    # the minutes-scale recompiles of big GSPMD programs across job restarts
    # (SURVEY §7.4.5) — the torch.compile cache analogue. NOTE: the jax
    # setting is process-global; "" does not reset a value set by an
    # earlier Trainer in the same process.
    compile_cache_dir: str = ""


@dataclass
class FaultsConfig:
    """Fault injection + recovery policies (faults/;
    docs/fault_tolerance.md has the point catalog, schedule grammar and
    recovery matrix)."""

    # Declarative injection schedule: each entry is
    # "<point>@key=val[:key=val...]", e.g.
    #   ("ckpt.save_io@step=3:count=2", "preempt.sigterm@step=5").
    # Keys: step (trainer step >= N), call (Nth traversal), p
    # (per-traversal probability, seeded by `seed`), count (times to
    # fire, default 1), gen (restart generation, default 0; -1 = all),
    # rc (step.crash exit code), delay (step.straggle seconds). The
    # PDTT_FAULTS env var appends more specs (subprocess workers,
    # serving tools).
    inject: tuple[str, ...] = ()
    # Seed for probabilistic (p=) specs — chaos soak reproducibility.
    seed: int = 0
    # SIGTERM → set-a-flag; the train loop forces a synchronized
    # checkpoint at the next step boundary, writes a `preempted` marker
    # in the summary record, and exits cleanly (preempt_exit_code) —
    # at most one step lost instead of save_every_steps. Off by
    # default: the legacy behavior (watchdog dumps diagnostics and
    # exits 143, fit()'s finally saves on the way down) remains.
    graceful_preemption: bool = False
    preempt_exit_code: int = 0
    # Retry policy for fault-guarded I/O (checkpoint save, record
    # decode): exponential backoff base*2^k capped at max, +jitter.
    retry_max_attempts: int = 3
    retry_base_delay_s: float = 0.05
    retry_max_delay_s: float = 2.0


@dataclass
class SentinelConfig:
    """Training health sentinel (sentinel/; docs/sentinel.md): numeric
    fault guard + auto-rewind + cross-host hang diagnosis — recovery for
    the faults that DON'T crash."""

    # Master switch for the numeric plane: in-graph update gate (a
    # non-finite grad/loss skips the optimizer update; params unchanged,
    # sentinel_skipped_steps_total{reason=nonfinite}), the rolling
    # loss-spike detector, and the auto-rewind loop. Off by default:
    # spike/streak tracking reads the loss to host every step, which
    # serializes async dispatch — a real (small) cost the operator opts
    # into.
    enabled: bool = False
    # Loss-spike detector (sentinel/numeric.py): a loss deviating from
    # the rolling-window median by more than spike_sigma robust sigmas
    # (MAD * 1.4826) — and by more than spike_min_rel of the median, the
    # floor that keeps a near-zero early MAD from flagging ordinary
    # jitter — counts as a bad step. Only healthy losses enter the
    # window, so divergence can't drag the baseline up after itself.
    spike_window: int = 64
    spike_sigma: float = 6.0
    spike_min_samples: int = 8
    spike_min_rel: float = 0.1
    # Auto-rewind: after this many CONSECUTIVE bad steps (non-finite or
    # spiking), restore the newest integrity-verified checkpoint
    # (latest_good_step), fast-forward the data stream to it (the exact
    # mid-epoch start_batch resume), scale the LR by lr_cooldown_factor
    # (compounds per rewind; persists in the checkpointed opt state) and
    # continue. max_rewinds bounds a run that keeps diverging — past it
    # the sentinel raises instead of looping restore-diverge forever.
    max_consecutive_bad: int = 3
    lr_cooldown_factor: float = 0.5
    max_rewinds: int = 8
    # Liveness plane (sentinel/liveness.py): with a tpurun store present
    # and hang_timeout_s > 0, every host publishes {step, ts} heartbeats
    # at heartbeat_every_steps cadence and rank 0 monitors staleness on
    # its OWN clock (clock-skew immune). On a hang: blamed-host
    # diagnosis (id + open spans), cluster-wide flight-recorder dump,
    # exit with hang_exit_code so the elastic agent gang-restarts.
    # Size hang_timeout_s well above a step time and the longest
    # checkpoint save; hosts that never heartbeat (first compile) are
    # never blamed. 0 = off.
    hang_timeout_s: float = 0.0
    hang_poll_s: float = 1.0
    hang_exit_code: int = 43
    heartbeat_every_steps: int = 1


@dataclass
class LoraConfig:
    """Parameter-efficient fine-tuning (lora.py). ``rank=0`` disables.

    Freeze the base model, train rank-r adapters on the projections whose
    param path matches ``targets``; merge for export with lora.strip().
    Beyond-reference capability (the [SPEC] harness has no PEFT) built on
    the same config/checkpoint interfaces (SURVEY H7/H8).
    """

    rank: int = 0
    alpha: float = 16.0
    # Regex over '/'-joined param paths; adapters attach to matching 2-D
    # Dense / 3-D DenseGeneral `kernel` leaves. Default covers the
    # llama/gpt2/bert/vit attention projections (torch-PEFT's customary
    # default is q/v only; we take all four — adapters are cheap, quality
    # is not).
    targets: str = (
        r"(q_proj|k_proj|v_proj|o_proj|query|key|value|attn_out"
        r"|attn/c_proj)/kernel$")
    # 3-D DenseGeneral kernels matching this regex are OUTPUT projections
    # — contracted (input) dims first, (H, Dh, d_out) — so the rank-r
    # factors bridge (H*Dh) -> out instead of in -> (H, Dh). Extend when
    # targeting a new model family whose out-projection has another name.
    out_proj_targets: str = r"(o_proj|attn_out|out_proj|attn/c_proj)/kernel$"
    # Additional full-rank leaves to leave trainable (regex, "" = none),
    # e.g. r"(final_norm|/bias$)" for norm-and-bias tuning a la BitFit.
    extra_trainable: str = ""
    # Warm-start: restore base params (only) from this run directory's
    # latest checkpoint before training — the "load pretrained, add
    # adapters" workflow. "" = train from fresh init (tests/debug).
    base_checkpoint: str = ""


@dataclass
class DistillConfig:
    """Knowledge distillation (distill.py). Enabled when
    ``teacher_checkpoint`` names a checkpoint directory; the teacher's
    architecture is read from that checkpoint's saved config, so nothing
    about the teacher is re-declared here."""

    teacher_checkpoint: str = ""
    # total = alpha * hard_loss + (1 - alpha) * kd_term
    alpha: float = 0.5
    # Softmax temperature for both teacher and student in the KD term
    # (the kd gradient is scaled by T^2 per Hinton et al. 2015).
    temperature: float = 2.0


@dataclass
class TrainConfig:
    """Root config. Serialises to/from JSON; dotted-path CLI overrides."""

    preset: str = ""
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    precision: PrecisionConfig = field(default_factory=PrecisionConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    faults: FaultsConfig = field(default_factory=FaultsConfig)
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)
    lora: LoraConfig = field(default_factory=LoraConfig)
    distill: DistillConfig = field(default_factory=DistillConfig)
    # Compute-graph optimization layer (steps.py / ops/fused_update.py):
    # microbatched scan step, overlapped bucketed collectives, fused
    # optimizer epilogue. docs/performance.md "Compute side".
    train: TrainStepConfig = field(default_factory=TrainStepConfig)
    # Train loop horizon: epochs if >0, else total_steps.
    epochs: int = 0
    total_steps: int = 1000
    eval_every_steps: int = 0  # 0 → eval at epoch boundaries only
    seed: int = 42
    # Loss: "softmax_xent" (classification) | "mlm_xent" |
    # "causal_lm_xent" | "seq2seq_xent" | "fused_causal_lm_xent" |
    # "dpo" (preference pairs vs the frozen reference named by
    # distill.teacher_checkpoint; losses.make_dpo_loss)
    loss: str = "softmax_xent"
    # DPO temperature (the beta in -log sigmoid(beta * margin))
    dpo_beta: float = 0.1
    # torch CrossEntropyLoss(label_smoothing=) analogue (softmax_xent only)
    label_smoothing: float = 0.0

    # ------------------------------------------------------------------ io
    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TrainConfig":
        kwargs: dict[str, Any] = {}
        for name in _fields(cls):
            if name not in d:
                continue
            v = d[name]
            if name in _SECTIONS:
                kwargs[name] = _SECTIONS[name](**_coerce_section(_SECTIONS[name], v))
            else:
                kwargs[name] = v
        return cls(**kwargs)

    @classmethod
    def from_json(cls, s: str) -> "TrainConfig":
        return cls.from_dict(json.loads(s))

    # ------------------------------------------------------- dotted access
    def override(self, dotted: str, value: str) -> None:
        """Apply one ``section.field=value`` override, coercing to the field type."""
        parts = dotted.split(".")
        obj: Any = self
        for p in parts[:-1]:
            if not hasattr(obj, p):
                raise KeyError(f"no config section {p!r} in {dotted!r}")
            obj = getattr(obj, p)
        leaf = parts[-1]
        if not hasattr(obj, leaf):
            raise KeyError(f"no config field {leaf!r} in {dotted!r}")
        cur = getattr(obj, leaf)
        setattr(obj, leaf, _coerce(value, cur))

    def apply_overrides(self, pairs: list[str]) -> None:
        for pair in pairs:
            if "=" not in pair:
                raise ValueError(f"override must be key=value, got {pair!r}")
            k, v = pair.split("=", 1)
            self.override(k.strip(), v.strip())


_SECTIONS = {
    "model": ModelConfig,
    "data": DataConfig,
    "optim": OptimConfig,
    "precision": PrecisionConfig,
    "mesh": MeshConfig,
    "checkpoint": CheckpointConfig,
    "obs": ObsConfig,
    "faults": FaultsConfig,
    "sentinel": SentinelConfig,
    "lora": LoraConfig,
    "distill": DistillConfig,
    "train": TrainStepConfig,
}


def _coerce_section(cls, d: dict[str, Any]) -> dict[str, Any]:
    names = _fields(cls)
    out = {}
    for k, v in d.items():
        if k in names:
            if isinstance(v, list):
                v = tuple(v)
            out[k] = v
    return out


def _coerce(value: str, current: Any) -> Any:
    """Coerce a CLI string to the type of the current value."""
    if isinstance(current, bool):
        if value.lower() in ("1", "true", "yes", "on"):
            return True
        if value.lower() in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"bad bool {value!r}")
    if isinstance(current, int):
        return int(value)
    if isinstance(current, float):
        return float(value)
    if isinstance(current, tuple):
        return tuple(x.strip() for x in value.split(",") if x.strip())
    return value


# ============================================================== presets
# The BASELINE.json:7-11 acceptance matrix.

def _resnet18_cifar10() -> TrainConfig:
    """BASELINE.json:7 — ResNet-18 on CIFAR-10, single-process smoke config."""
    c = TrainConfig(preset="resnet18_cifar10")
    c.model = ModelConfig(name="resnet18", num_classes=10, image_size=32)
    c.data = DataConfig(dataset="cifar10", batch_size=128)
    c.optim = OptimConfig(
        name="momentum", learning_rate=0.1, momentum=0.9, weight_decay=5e-4,
        schedule="cosine", warmup_steps=200,
    )
    c.epochs = 30
    c.loss = "softmax_xent"
    # reference-genre recipe: keep the best-val-accuracy checkpoint
    c.checkpoint.best_metric = "accuracy"
    return c


def _resnet50_imagenet() -> TrainConfig:
    """BASELINE.json:8 — ResNet-50 / ImageNet, DDP all-reduce → data-parallel mesh."""
    c = TrainConfig(preset="resnet50_imagenet")
    c.model = ModelConfig(name="resnet50", num_classes=1000, image_size=224)
    c.data = DataConfig(dataset="imagenet_folder", batch_size=1024, num_workers=16)
    c.optim = OptimConfig(
        name="momentum", learning_rate=0.4, momentum=0.9, weight_decay=1e-4,
        schedule="cosine", warmup_steps=2500, nesterov=False,
    )
    c.precision = PrecisionConfig(compute_dtype="bfloat16")
    c.mesh = MeshConfig(data=-1)
    c.epochs = 90
    c.loss = "softmax_xent"
    # reference-genre recipe: keep the best-val-accuracy checkpoint
    c.checkpoint.best_metric = "accuracy"
    return c


def _vit_b16_imagenet() -> TrainConfig:
    """BASELINE.json:9 — ViT-B/16, bf16 mixed precision + grad accumulation."""
    c = TrainConfig(preset="vit_b16_imagenet")
    c.model = ModelConfig(
        name="vit_b16", num_classes=1000, image_size=224, patch_size=16,
        hidden_size=768, num_layers=12, num_heads=12, mlp_dim=3072,
        dropout_rate=0.1,
    )
    c.data = DataConfig(dataset="imagenet_folder", batch_size=4096, num_workers=16)
    c.optim = OptimConfig(
        name="adamw", learning_rate=3e-3, weight_decay=0.3, beta2=0.999,
        schedule="cosine", warmup_steps=10000, accum_steps=4, grad_clip_norm=1.0,
        # timm recipe: no decay on bias/norm, nor on cls_token/pos_embed
        # (timm's ViT no_weight_decay() set)
        decay_exclude=r"bias$,scale$,cls_token$,pos_embed$",
    )
    c.precision = PrecisionConfig(compute_dtype="bfloat16")
    c.epochs = 300
    c.loss = "softmax_xent"
    # reference-genre recipe: keep the best-val-accuracy checkpoint
    c.checkpoint.best_metric = "accuracy"
    return c


def _bert_base_mlm() -> TrainConfig:
    """BASELINE.json:10 — BERT-base MLM on Wikipedia, LAMB optimizer."""
    c = TrainConfig(preset="bert_base_mlm")
    c.model = ModelConfig(
        name="bert_base", hidden_size=768, num_layers=12, num_heads=12,
        mlp_dim=3072, vocab_size=30522, max_seq_len=512, dropout_rate=0.1,
    )
    c.data = DataConfig(dataset="text_mlm", batch_size=256, seq_len=512, mlm_prob=0.15)
    c.optim = OptimConfig(
        name="lamb", learning_rate=1.75e-3, weight_decay=0.01,
        schedule="linear", warmup_steps=3125, grad_clip_norm=1.0,
        # BERT recipe's no_decay = ['bias', 'LayerNorm.weight']
        decay_exclude=r"bias$,scale$",
    )
    c.precision = PrecisionConfig(compute_dtype="bfloat16")
    c.total_steps = 28125
    c.loss = "mlm_xent"
    return c


def _llama2_7b() -> TrainConfig:
    """BASELINE.json:11 — Llama-2 7B pretrain; FSDP → GSPMD param sharding."""
    c = TrainConfig(preset="llama2_7b")
    c.model = ModelConfig(
        name="llama", hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=32, mlp_dim=11008, vocab_size=32000, max_seq_len=4096,
        rope_theta=10000.0, rms_norm_eps=1e-5, remat=True,
        # (B,S,V) logits at 32k vocab / 4k seq are ~2 GB fp32 per sample —
        # the fused chunked head (losses.chunked_causal_ce) never builds
        # them; generation clears the flag automatically.
        fused_lm_loss=True,
    )
    c.data = DataConfig(dataset="synthetic_lm", batch_size=128, seq_len=4096)
    c.optim = OptimConfig(
        name="adamw", learning_rate=3e-4, weight_decay=0.1, beta2=0.95,
        schedule="cosine", warmup_steps=2000, grad_clip_norm=1.0,
        decay_exclude=r"scale$",  # no decay on RMSNorm scales (no biases in llama)
    )
    c.precision = PrecisionConfig(compute_dtype="bfloat16")
    c.mesh = MeshConfig(data=1, fsdp=-1)
    c.total_steps = 500000
    c.loss = "fused_causal_lm_xent"  # pairs with model.fused_lm_loss above
    return c


def _gpt2_small() -> TrainConfig:
    """GPT-2 124M pretrain (model-zoo extension beyond the BASELINE matrix;
    HF-checkpoint-compatible via interop's 'gpt2' mapping)."""
    c = TrainConfig(preset="gpt2_small")
    c.model = ModelConfig(
        name="gpt2", hidden_size=768, num_layers=12, num_heads=12,
        # 50257 padded to 50304 (2^7·393): the standard GPT-2 trick — the
        # true vocab is indivisible by any power-of-2 mesh, which would
        # silently replicate wte (the largest param) instead of fsdp-
        # sharding it (parallel/partition.py validate_spec fallback).
        mlp_dim=3072, vocab_size=50304, max_seq_len=1024, dropout_rate=0.1,
    )
    c.data = DataConfig(dataset="synthetic_lm", batch_size=64, seq_len=1024)
    c.optim = OptimConfig(
        name="adamw", learning_rate=6e-4, weight_decay=0.1, beta2=0.95,
        schedule="cosine", warmup_steps=2000, grad_clip_norm=1.0,
        decay_exclude=r"bias$,scale$",  # decay only matmul/embedding weights
    )
    c.precision = PrecisionConfig(compute_dtype="bfloat16")
    c.mesh = MeshConfig(data=-1)
    c.total_steps = 600000
    c.loss = "causal_lm_xent"
    return c


def _mixtral_8x7b() -> TrainConfig:
    """Mixtral-8x7B-style sparse-MoE decoder (model-zoo extension): the
    llama family with GShard top-2 routing over 8 experts, GQA (8 kv
    heads), sliding-window attention, and rope_theta=1e6. Mesh splits
    experts over their own axis beside fsdp (SURVEY §2.3 EP)."""
    c = TrainConfig(preset="mixtral_8x7b")
    c.model = ModelConfig(
        name="llama", hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, mlp_dim=14336, vocab_size=32000, max_seq_len=4096,
        rope_theta=1e6, rms_norm_eps=1e-5, remat=True, fused_lm_loss=True,
        attention_window=4096,
        num_experts=8, expert_top_k=2, moe_aux_weight=0.02,
    )
    c.data = DataConfig(dataset="synthetic_lm", batch_size=128, seq_len=4096)
    c.optim = OptimConfig(
        name="adamw", learning_rate=3e-4, weight_decay=0.1, beta2=0.95,
        schedule="cosine", warmup_steps=2000, grad_clip_norm=1.0,
        decay_exclude=r"scale$",
    )
    c.precision = PrecisionConfig(compute_dtype="bfloat16")
    c.mesh = MeshConfig(data=1, expert=8, fsdp=-1)
    c.total_steps = 500000
    c.loss = "fused_causal_lm_xent"
    return c


def _t5_small() -> TrainConfig:
    """T5-small seq2seq pretrain (model-zoo extension beyond the BASELINE
    matrix). HF-layout-compatible via interop's 't5' mapping
    (feed_forward_proj='relu'); trains an UNTIED head — to load published
    tied v1.0 checkpoints set model.tie_word_embeddings=true."""
    c = TrainConfig(preset="t5_small")
    c.model = ModelConfig(
        name="t5", hidden_size=512, num_layers=6, decoder_layers=6,
        num_heads=8, mlp_dim=2048, vocab_size=32128, max_seq_len=512,
        dropout_rate=0.1,
    )
    c.data = DataConfig(dataset="synthetic_seq2seq", batch_size=128,
                        seq_len=512, tgt_seq_len=128)
    c.optim = OptimConfig(
        # The T5 paper trains with Adafactor; inverse-sqrt decay is
        # approximated with cosine here (the schedule families in
        # optim.make_schedule).
        name="adafactor", learning_rate=1e-2, weight_decay=0.0,
        schedule="cosine", warmup_steps=10000, grad_clip_norm=1.0,
    )
    c.precision = PrecisionConfig(compute_dtype="bfloat16")
    c.mesh = MeshConfig(data=-1)
    c.total_steps = 500000
    c.loss = "seq2seq_xent"
    return c


_PRESETS = {
    "resnet18_cifar10": _resnet18_cifar10,
    "resnet50_imagenet": _resnet50_imagenet,
    "vit_b16_imagenet": _vit_b16_imagenet,
    "bert_base_mlm": _bert_base_mlm,
    "llama2_7b": _llama2_7b,
    "gpt2_small": _gpt2_small,
    "t5_small": _t5_small,
    "mixtral_8x7b": _mixtral_8x7b,
}


def list_presets() -> list[str]:
    return sorted(_PRESETS)


def get_preset(name: str) -> TrainConfig:
    if name not in _PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {list_presets()}")
    return _PRESETS[name]()
