"""Per-request SLO accounting: the serving plane's measurement half.

Every request the BatcherService admits gets a lifecycle record —
submit, queue exit, first token, per-tick token arrivals, finish — and
the tracker turns those into the latency numbers a serving fleet is
actually judged on:

- **TTFT** (submit → first token): the user-visible "it started".
- **inter-token latency**: the streaming cadence; its tail is what a
  slow decode step / straggling replica shows up in first.
- **queue wait** (submit → admission): the overload signal admission
  control throttles on.
- **tokens/s** per finished request, and request outcomes by class
  (``ok`` / ``deadline`` / ``shed`` / ``timeout`` / ``abandoned`` /
  ``cancelled`` / ``leak``).

Samples land in BOTH a rolling window (p50/p95/p99 in ``snapshot()``,
the /healthz surface the router balances on) and the process obs
registry (``serve_ttft_seconds`` etc. histograms — the Prometheus
scrape). Deadlines ride the same records: each request may carry an
absolute expiry (monotonic clock); ``expired()`` is what the service
loop sweeps between decode steps.

Thread model: called under the BatcherService lock for mutation;
``snapshot()`` is called WITHOUT it from /healthz (a health probe must
not block behind a wedged decode), so the internal lock here only
guards the record dict and windows — O(window) worst case, never
device work.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from pytorch_distributed_train_tpu.obs.registry import get_registry

# finer than the span-duration default: TTFT/inter-token targets live in
# the 1 ms .. 10 s range
_LAT_BUCKETS = tuple(0.001 * 2 ** i for i in range(15))

OUTCOMES = ("ok", "deadline", "shed", "timeout", "abandoned",
            "cancelled", "leak", "error", "session_evicted")


def percentile(sorted_xs: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted window (tiny n —
    the rolling windows here — so exactness beats interpolation)."""
    if not sorted_xs:
        return 0.0
    i = min(len(sorted_xs) - 1, max(0, int(q * len(sorted_xs))))
    return sorted_xs[i]


@dataclasses.dataclass
class _Req:
    t_submit: float
    deadline_ts: float | None = None   # monotonic expiry, None = none
    t_admit: float | None = None
    t_last: float | None = None        # last token arrival
    tokens: int = 0


class SloTracker:
    def __init__(self, window: int = 512):
        self._lock = threading.Lock()
        self._reqs: dict[int, _Req] = {}
        self._ttft: deque[float] = deque(maxlen=window)
        self._itl: deque[float] = deque(maxlen=window)
        self._queue_wait: deque[float] = deque(maxlen=window)
        self._tok_s: deque[float] = deque(maxlen=window)
        self.outcomes: dict[str, int] = {o: 0 for o in OUTCOMES}

    # ------------------------------------------------------------ lifecycle
    def on_submit(self, uid: int, deadline_ts: float | None,
                  now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._reqs[uid] = _Req(t_submit=now, deadline_ts=deadline_ts)

    def on_admit(self, uid: int, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            r = self._reqs.get(uid)
            if r is None or r.t_admit is not None:
                return
            r.t_admit = now
            wait = max(0.0, now - r.t_submit)
            self._queue_wait.append(wait)
        get_registry().histogram(
            "serve_queue_wait_seconds", buckets=_LAT_BUCKETS,
            help="submit -> admission wait per request").observe(wait)

    def on_tokens(self, uid: int, k: int, now: float | None = None
                  ) -> float | None:
        """``k`` new tokens surfaced for ``uid``. Returns the TTFT
        sample when these are the request's FIRST tokens (the caller
        feeds it to the tail-latency monitor), else None."""
        if k <= 0:
            return None
        now = time.monotonic() if now is None else now
        ttft = None
        itl = None
        with self._lock:
            r = self._reqs.get(uid)
            if r is None:
                return None
            if r.t_last is None:
                ttft = max(0.0, now - r.t_submit)
                self._ttft.append(ttft)
                if r.t_admit is None:
                    # admission and first token are one event for the
                    # causal batcher (admission samples token one)
                    r.t_admit = now
                    self._queue_wait.append(
                        max(0.0, now - r.t_submit))
            else:
                itl = max(0.0, now - r.t_last) / k
                self._itl.append(itl)
            r.t_last = now
            r.tokens += k
        reg = get_registry()
        if ttft is not None:
            reg.histogram(
                "serve_ttft_seconds", buckets=_LAT_BUCKETS,
                help="submit -> first token per request").observe(ttft)
        if itl is not None and ttft is None:
            reg.histogram(
                "serve_inter_token_seconds", buckets=_LAT_BUCKETS,
                help="per-token decode cadence (batched step "
                     "quantum / tokens surfaced)").observe(itl)
        return ttft

    def on_finish(self, uid: int, outcome: str,
                  now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        outcome = outcome if outcome in OUTCOMES else "error"
        with self._lock:
            r = self._reqs.pop(uid, None)
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            if r is not None and outcome == "ok" and r.tokens > 0:
                dur = max(1e-9, now - r.t_submit)
                self._tok_s.append(r.tokens / dur)
        reg = get_registry()
        reg.counter("serve_requests_total", labels={"outcome": outcome},
                    help="finished serving requests by outcome").inc()
        if r is not None and outcome == "ok":
            reg.histogram(
                "serve_request_seconds",
                help="submit -> finish per completed request").observe(
                    max(0.0, now - r.t_submit))

    # ------------------------------------------------------------ deadlines
    def shed(self) -> None:
        """A request refused at the door (never got a uid/record)."""
        with self._lock:
            self.outcomes["shed"] = self.outcomes.get("shed", 0) + 1
        get_registry().counter(
            "serve_requests_total", labels={"outcome": "shed"},
            help="finished serving requests by outcome").inc()

    def expired(self, now: float | None = None) -> list[int]:
        """uids whose deadline has passed, oldest-submitted first."""
        now = time.monotonic() if now is None else now
        with self._lock:
            out = [(r.t_submit, uid) for uid, r in self._reqs.items()
                   if r.deadline_ts is not None and now > r.deadline_ts]
        return [uid for _, uid in sorted(out)]

    def oldest_inflight(self) -> int | None:
        """The longest-waiting tracked request — what the
        ``serve.deadline`` drill point force-expires."""
        with self._lock:
            if not self._reqs:
                return None
            return min(self._reqs.items(),
                       key=lambda kv: kv[1].t_submit)[0]

    def inflight(self) -> int:
        with self._lock:
            return len(self._reqs)

    # ------------------------------------------------------------- report
    def est_ttft_s(self, queue_depth: int, slots: int) -> float:
        """Admission-control estimate of a NEW request's TTFT: the
        recent p50 scaled by how many queued requests must admit ahead
        of it (each admission is one prefill quantum; ``slots`` of them
        drain per wave). Deliberately simple and monotone in depth —
        the knob it feeds (``shed_ttft_s``) is a shed threshold, not a
        promise."""
        with self._lock:
            xs = sorted(self._ttft)
        p50 = percentile(xs, 0.50)
        return p50 * (1.0 + queue_depth / max(1, slots))

    def snapshot(self) -> dict:
        """Flat dict for /healthz + obs_report: rolling p50/p95/p99 of
        every SLO series (seconds) + outcome counts."""
        with self._lock:
            ttft = sorted(self._ttft)
            itl = sorted(self._itl)
            qw = sorted(self._queue_wait)
            toks = sorted(self._tok_s)
            outcomes = dict(self.outcomes)
            inflight = len(self._reqs)
        out = {"inflight": inflight,
               "outcomes": {k: v for k, v in outcomes.items() if v}}
        for name, xs in (("ttft_s", ttft), ("inter_token_s", itl),
                         ("queue_wait_s", qw)):
            out[name] = {"n": len(xs),
                         "p50": round(percentile(xs, 0.50), 6),
                         "p95": round(percentile(xs, 0.95), 6),
                         "p99": round(percentile(xs, 0.99), 6)}
        out["tokens_per_s_p50"] = round(percentile(toks, 0.50), 3)
        return out
