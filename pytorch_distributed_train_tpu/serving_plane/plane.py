"""ReliabilityPlane: the facade BatcherService threads the plane through.

One object owns the request-path reliability state of a serving
process: SLO tracking (slo.py), admission control (admission.py), the
tail-latency monitor (anomaly.py), deadline bookkeeping, and a
goodput-style decomposition of the scheduler loop's wall time
(prefill / decode / stalled / idle — obs/goodput.py with the serving
vocabulary). ``tools/serve_http.py`` builds one from its CLI knobs and
calls into it from exactly three places:

- handler threads at intake: ``admit_or_raise`` (→ 429 +
  ``Retry-After``), ``resolve_deadline`` + ``on_submit``;
- the scheduler loop after each step quantum: ``on_admitted`` /
  ``on_tokens`` / ``on_finish`` and the two sweeps —
  ``take_expired`` (deadlines → cancel + 504; also where the
  ``serve.deadline`` drill point force-expires the oldest request)
  and the service's slot-leak sweep (which reports through
  ``note_leak``);
- ``/healthz``: ``snapshot`` (lock-free with respect to the scheduler).

Everything here is host-side Python over plain floats — the plane adds
no device work to the request path.
"""

from __future__ import annotations

import time

from pytorch_distributed_train_tpu.faults import maybe_fire as _maybe_fire
from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs import tracing
from pytorch_distributed_train_tpu.obs.goodput import (
    SERVE_BUCKETS,
    GoodputTracker,
)
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.serving_plane.admission import (
    AdmissionController,
)
from pytorch_distributed_train_tpu.serving_plane.slo import SloTracker


class OverloadShed(RuntimeError):
    """Admission refused: answer 429 with ``retry_after_s``."""

    def __init__(self, retry_after_s: float, message: str):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(RuntimeError):
    """The request's wall-clock budget expired: answer 504. The
    batcher-side cancel already reclaimed its slot/KV."""


class ReliabilityPlane:
    def __init__(self, *, max_queue_depth: int = 0,
                 shed_ttft_s: float = 0.0,
                 deadline_default_s: float = 0.0,
                 deadline_max_s: float = 0.0,
                 slots: int = 1, slo_window: int = 512,
                 monitor=None):
        self.slo = SloTracker(window=slo_window)
        self.admission = AdmissionController(
            max_queue_depth=max_queue_depth, shed_ttft_s=shed_ttft_s)
        self.monitor = monitor
        self.slots = max(1, int(slots))
        self.deadline_default_s = float(deadline_default_s)
        self.deadline_max_s = float(deadline_max_s)
        self.goodput = GoodputTracker(buckets=SERVE_BUCKETS,
                                      productive=("prefill", "decode"))

    # ------------------------------------------------------------- intake
    def resolve_deadline(self, requested_s) -> float | None:
        """Per-request budget seconds → absolute monotonic expiry (or
        None when deadlines are off for this request). The server
        default applies when the request carries none; ``deadline_max_s``
        caps what a client may ask for (a fleet knob: one greedy client
        must not park on a slot for an hour)."""
        budget = (self.deadline_default_s if requested_s is None
                  else float(requested_s))
        if budget <= 0:
            return None
        if self.deadline_max_s > 0:
            budget = min(budget, self.deadline_max_s)
        return time.monotonic() + budget

    def admit_or_raise(self, queue_depth: int) -> None:
        if not self.admission.enabled:
            return
        est = self.slo.est_ttft_s(queue_depth, self.slots)
        retry_after = self.admission.check(queue_depth, est)
        if retry_after is None:
            return
        self.slo.shed()
        # a shed request is exactly the kind of tail the sampler must
        # retain: flag the caller's active trace (handler thread scope)
        tracing.flag_current("shed")
        get_registry().counter(
            "serve_shed_total",
            help="requests refused by admission control (429)").inc()
        events_lib.emit("serve", "request_shed", queue_depth=queue_depth,
                        est_ttft_ms=round(est * 1e3, 1),
                        retry_after_s=retry_after)
        raise OverloadShed(
            retry_after, f"overloaded: queue depth {queue_depth}, "
            f"estimated TTFT {est:.2f}s — retry after {retry_after:.0f}s")

    def admission_state(self, queue_depth: int) -> str:
        if not self.admission.enabled:
            return "ok"
        return self.admission.state(
            queue_depth, self.slo.est_ttft_s(queue_depth, self.slots))

    # --------------------------------------------------------- step loop
    def on_submit(self, uid: int, deadline_ts: float | None,
                  now: float | None = None) -> None:
        self.slo.on_submit(uid, deadline_ts, now=now)

    def on_admitted(self, uid: int, now: float | None = None) -> None:
        self.slo.on_admit(uid, now=now)

    def on_tokens(self, uid: int, k: int,
                  now: float | None = None) -> bool:
        """Returns True when THIS request's TTFT tripped the tail
        detector — the caller flags the request's trace so the very
        sample that fired the anomaly is retained."""
        ttft = self.slo.on_tokens(uid, k, now=now)
        if self.monitor is not None and ttft is not None:
            return self.monitor.observe_ttft(ttft, now=now)
        return False

    def on_inter_token(self, s: float, now: float | None = None) -> None:
        """Per-tick decode-cadence sample (step quantum / tokens
        surfaced) — fed by the scheduler loop once per step so the
        detector sees the batcher's cadence even when every consumer
        is a non-streaming waiter."""
        if self.monitor is not None and s > 0:
            self.monitor.observe_inter_token(s, now=now)

    def on_finish(self, uid: int, outcome: str,
                  now: float | None = None) -> None:
        self.slo.on_finish(uid, outcome, now=now)

    def take_expired(self, now: float | None = None) -> list[int]:
        """uids to cancel-and-504 this sweep: real deadline expiries
        plus (``serve.deadline`` drill) a forced expiry of the oldest
        in-flight request."""
        now = time.monotonic() if now is None else now
        expired = self.slo.expired(now=now)
        if self.slo.inflight() and _maybe_fire("serve.deadline"):
            forced = self.slo.oldest_inflight()
            if forced is not None and forced not in expired:
                expired.append(forced)
        if expired:
            get_registry().counter(
                "serve_deadline_expired_total",
                help="requests cancelled at their deadline (504)").inc(
                    len(expired))
        return expired

    def note_leak(self, uid: int, where: str) -> None:
        """The service's slot-leak sweep found (and reclaimed) a slot
        whose waiter died — count it, journal it, close the record."""
        get_registry().counter(
            "serve_slot_leaks_total",
            help="KV slots found held with no live waiter (reclaimed "
                 "by the leak sweep)").inc()
        events_lib.emit("serve", "slot_leak", uid=uid, where=where)
        self.slo.on_finish(uid, "leak")

    # ------------------------------------------------------------- report
    def snapshot(self, queue_depth: int, slot_accounting: dict) -> dict:
        """The /healthz reliability section: admission state, queue
        depth, slot occupancy, SLO percentiles, goodput split."""
        return {
            "admission": self.admission_state(queue_depth),
            "queue_depth": queue_depth,
            "slots": slot_accounting,
            "slo": self.slo.snapshot(),
            "goodput": self.goodput.snapshot(),
        }
