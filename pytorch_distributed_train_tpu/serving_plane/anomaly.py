"""Tail-latency anomaly detection: the serving twin of the PR-5 hooks.

The sentinel's median+MAD ``SpikeDetector`` (sentinel/numeric.py) —
already pointed at losses (PR 3) and step times / input stalls (PR 5)
— here watches the two request-path series whose tails pages are
written about: TTFT per request and inter-token latency per decode
tick. Healthy-only windows, robust statistics, an absolute floor so a
sub-millisecond baseline cannot flag scheduler jitter — the same
failure model, a different clock.

On a spike the monitor:

1. journals an ``anomaly`` event (``ttft_regression`` /
   ``inter_token_regression``) — the category timeline_report builds
   causal chains from — plus a ``serve``/``tail_latency`` event so the
   request-path story reads complete in its own category;
2. optionally fires the PR-5 managed profiler: serving has no step
   counter, so the capture is the time-bounded ad-hoc kind
   (``capture_for_seconds``), cooldown-limited by WALL time the way
   the trainer's is by steps.
"""

from __future__ import annotations

import threading
import time

from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.sentinel.numeric import SpikeDetector


class TailLatencyMonitor:
    def __init__(self, *, window: int = 64, sigma: float = 6.0,
                 min_samples: int = 16, min_rel: float = 0.5,
                 profiler=None, capture_seconds: float = 2.0,
                 cooldown_s: float = 60.0):
        self._ttft_det = SpikeDetector(window=window, sigma=sigma,
                                       min_samples=min_samples,
                                       min_rel=min_rel)
        self._itl_det = SpikeDetector(window=window, sigma=sigma,
                                      min_samples=min_samples,
                                      min_rel=min_rel)
        self.profiler = profiler
        self.capture_seconds = capture_seconds
        self.cooldown_s = cooldown_s
        self._last_capture_ts: float | None = None

    def observe_ttft(self, s: float, now: float | None = None) -> bool:
        return self._observe(self._ttft_det, "ttft_regression", s, now)

    def observe_inter_token(self, s: float,
                            now: float | None = None) -> bool:
        return self._observe(self._itl_det, "inter_token_regression", s,
                             now)

    def _observe(self, det: SpikeDetector, kind: str, s: float,
                 now: float | None) -> bool:
        if not det.is_spike(s):
            det.add(s)
            return False
        # Re-baseline after firing (the PR-5 step-time stance): nothing
        # "recovers" a persistent latency shift on this host — without
        # the reset a regressed replica would journal one anomaly per
        # request forever. The fresh window adopts the new regime
        # within min_samples ticks.
        det.reset()
        self._anomaly(kind, s, time.monotonic() if now is None else now)
        return True

    def _anomaly(self, kind: str, value_s: float, now: float) -> None:
        events_lib.emit("anomaly", kind, latency_ms=round(value_s * 1e3, 3))
        events_lib.emit("serve", "tail_latency", kind=kind,
                        latency_ms=round(value_s * 1e3, 3))
        get_registry().counter(
            "serve_tail_anomalies_total", labels={"kind": kind},
            help="tail-latency detector firings on the request "
                 "path").inc()
        if self.profiler is None:
            return
        if (self._last_capture_ts is not None
                and now - self._last_capture_ts < self.cooldown_s):
            return
        self._last_capture_ts = now

        # Concurrency-plane true positive (lock-order graph + syncdbg
        # hold_while_blocking): observe_* runs on the serve scheduler
        # UNDER the service lock, and a capture start is blocking work
        # (profiler lock, capture-dir mkdir, jax profiler start) —
        # every intake/shed/healthz handler would stall behind it. The
        # capture is fired off-thread; the cooldown stamp above stays
        # on the calling thread so a burst still fires exactly once.
        def _capture():
            try:
                # reason == anomaly kind: timeline_report's causal-chain
                # matcher pairs the capture with THIS anomaly by it
                self.profiler.capture_for_seconds(self.capture_seconds,
                                                  reason=kind)
            except Exception as e:  # noqa: BLE001 — must outlive it
                print(f"[serve] tail-latency capture failed "
                      f"({type(e).__name__}: {e})", flush=True)

        threading.Thread(target=_capture, daemon=True,
                         name="tail-latency-capture").start()
