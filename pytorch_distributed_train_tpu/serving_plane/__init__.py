"""Serving reliability plane: the request-path twin of the training
planes (docs/serving_reliability.md).

PRs 1-6 taught *training* to detect, survive, diagnose and profile
every failure mode we inject; this package gives the continuous
batcher behind ``tools/serve_http.py`` the complementary story —
requests that time out (deadlines → 504 with the KV slot reclaimed),
shed (bounded admission → 429 + Retry-After instead of collapse),
hedge and fail over across replicas (serving_plane/router.py behind
``tools/serve_router.py``) — instrumented through the SAME obs planes:
SLO metrics into the registry, a ``serve`` event-journal category, and
tail-latency anomalies that can fire the PR-5 managed profiler.

Layout:

- ``slo.py``        — per-request SLO lifecycle (queue wait, TTFT,
                      inter-token percentiles, tokens/s) + deadlines
- ``admission.py``  — bounded-queue load shedding (429 + Retry-After)
- ``anomaly.py``    — median+MAD tail-latency detector (sentinel math)
                      with a managed-profiler capture hook
- ``plane.py``      — ``ReliabilityPlane``: the facade BatcherService
                      threads through submit / step / finish
- ``router.py``     — multi-replica routing core (health, least-
                      outstanding balancing, retry, hedging, rolling
                      restart) for ``tools/serve_router.py``
- ``testing.py``    — deterministic fakes (token batcher, profiler
                      backend) shared by tests and ``tools/slo_soak.py``

No jax at module scope anywhere in this package (the obs/ contract):
the router and the fakes must run on a login host / in a subprocess
without touching a device backend.
"""

from pytorch_distributed_train_tpu.serving_plane.admission import (
    AdmissionController,
)
from pytorch_distributed_train_tpu.serving_plane.anomaly import (
    TailLatencyMonitor,
)
from pytorch_distributed_train_tpu.serving_plane.plane import (
    DeadlineExceeded,
    OverloadShed,
    ReliabilityPlane,
)
from pytorch_distributed_train_tpu.serving_plane.slo import SloTracker

__all__ = [
    "AdmissionController",
    "DeadlineExceeded",
    "OverloadShed",
    "ReliabilityPlane",
    "SloTracker",
    "TailLatencyMonitor",
]
