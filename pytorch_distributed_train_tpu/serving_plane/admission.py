"""Admission control: overload must degrade, never collapse.

An unbounded intake queue turns overload into the worst possible
failure mode: every request is accepted, every request times out, and
the batcher burns its decode budget on work whose waiters are long
gone. The controller bounds the queue two ways:

- **depth** (``max_queue_depth``): a hard cap on requests waiting for
  a slot — the classic bounded-queue shed.
- **estimated TTFT** (``shed_ttft_s``): shed once the p50-based
  estimate of a NEW request's time-to-first-token exceeds the knob —
  depth alone misreads a fleet where each queued request is cheap (or
  expensive); latency is what the SLO is written in.

A shed answer is HTTP 429 with ``Retry-After`` (the estimate, bounded)
so well-behaved clients back off instead of hammering; the state
(``ok`` / ``shedding``) is exported on /healthz so the router stops
picking a shedding replica before its clients ever see the 429s.

Both knobs 0 = off (the default: existing single-user deployments keep
their unbounded behavior).
"""

from __future__ import annotations

import math


class AdmissionController:
    def __init__(self, max_queue_depth: int = 0, shed_ttft_s: float = 0.0,
                 retry_after_max_s: float = 30.0):
        if max_queue_depth < 0 or shed_ttft_s < 0:
            raise ValueError(
                f"admission knobs must be >= 0 (0 = off), got "
                f"max_queue_depth={max_queue_depth} "
                f"shed_ttft_s={shed_ttft_s}")
        self.max_queue_depth = int(max_queue_depth)
        self.shed_ttft_s = float(shed_ttft_s)
        self.retry_after_max_s = float(retry_after_max_s)

    @property
    def enabled(self) -> bool:
        return bool(self.max_queue_depth or self.shed_ttft_s)

    def check(self, queue_depth: int, est_ttft_s: float) -> float | None:
        """None = admit; else the Retry-After to answer the shed with.
        The retry hint is the TTFT estimate when latency shed, else a
        depth-proportional guess — clamped to [1, retry_after_max_s]
        and integral (the HTTP header is delta-seconds)."""
        over_depth = (self.max_queue_depth
                      and queue_depth >= self.max_queue_depth)
        over_ttft = (self.shed_ttft_s
                     and est_ttft_s > self.shed_ttft_s)
        if not (over_depth or over_ttft):
            return None
        hint = est_ttft_s if over_ttft else max(1.0, est_ttft_s)
        return float(min(self.retry_after_max_s,
                         max(1.0, math.ceil(hint))))

    def state(self, queue_depth: int, est_ttft_s: float) -> str:
        return ("shedding" if self.check(queue_depth, est_ttft_s)
                is not None else "ok")
