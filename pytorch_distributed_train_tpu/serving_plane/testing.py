"""Deterministic fakes for the serving reliability plane.

The plane's whole job is policing timing and lifecycle, which a real
model on a 2-core CI box makes both slow and noisy. These fakes give
tests, ``tools/slo_soak.py`` and ``tools/serve_http.py --fake-backend``
a batcher with the EXACT scheduler contract of
``serving.ContinuousBatcher`` (queue / step / cancel / sessions /
streaming tap / slot accounting) but a pure-Python token source with a
controllable per-step delay — so deadline, admission, leak and router
tests measure the plane, not XLA compile time.

Importing this module pulls serving.py (for the Request/Completion
wire types the service consumes) and therefore jax — but never builds
a model or touches a device, so a --fake-backend replica subprocess
boots in import time, not compile time; that is what makes the
multi-replica router drill testable at all.
"""

from __future__ import annotations

import time
from collections import deque

from pytorch_distributed_train_tpu.serving import Completion, Request


class FakeByteTok:
    """Byte-level tokenizer stand-in (encode = raw bytes; decode is
    printable-ascii so SSE deltas stay valid JSON). ``eos_id`` None:
    fake completions finish by length only — deterministic durations
    are the point."""

    eos_id = None

    def encode(self, text: str) -> list[int]:
        return [b % 256 for b in text.encode("utf-8")] or [0]

    def decode(self, ids) -> str:
        return "".join(chr(97 + (int(t) % 26)) for t in ids)


class FakeTokenBatcher:
    """ContinuousBatcher-shaped token mill.

    Tokens are a pure function of (prompt, uid, position) so two forks
    of one prompt differ (the ``n>1`` path needs distinct choices) and
    reruns are bit-stable. ``step_delay_s`` sleeps once per step() —
    the decode-quantum knob deadline/tail tests turn."""

    supports_sessions = True

    def __init__(self, *, slots: int = 4, step_delay_s: float = 0.0,
                 vocab: int = 250):
        self.slots = slots
        self.step_delay_s = step_delay_s
        self.vocab = vocab
        self.queue: deque[Request] = deque()
        self._next_uid = 0
        self._req: list[Request | None] = [None] * slots
        self._generated: list[list[int]] = [[] for _ in range(slots)]
        self._parked: dict[int, int] = {}  # sid -> slot
        self._parked_slots: set[int] = set()
        self.stats = {"steps": 0, "prefills": 0, "preloads": 0,
                      "resumes": 0, "forks": 0, "generated_tokens": 0,
                      "admit_ms": 0.0, "device_ms": 0.0, "host_ms": 0.0}

    # ------------------------------------------------------------ intake
    def submit(self, prompt, max_new_tokens: int, *, temperature=0.0,
               eos_id=None, keep=False, session=None, prefix=None,
               **_kw) -> int:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if session is not None and session not in self._parked:
            raise ValueError(f"unknown session {session}")
        if prefix is not None and prefix not in self._parked:
            raise ValueError(f"unknown session {prefix}")
        uid = self._next_uid
        self._next_uid += 1
        self.queue.append(Request(uid, prompt, max_new_tokens,
                                  temperature, eos_id, keep=keep,
                                  session=session, prefix=prefix))
        return uid

    def _evict_parked(self) -> int | None:
        """Free the oldest parked session not referenced by a queued
        continuation — the real batcher's LRU-eviction contract, which
        can_preload()'s True answer promises preload() will honor."""
        queued = {q.session for q in self.queue if q.session is not None}
        queued |= {q.prefix for q in self.queue if q.prefix is not None}
        for sid in list(self._parked):
            if sid in queued:
                continue
            r = self._parked.pop(sid)
            self._parked_slots.discard(r)
            return r
        return None

    def preload(self, prompt) -> int:
        r = self._free_slot()
        if r is None:
            r = self._evict_parked()
        if r is None:
            raise RuntimeError("no slot available for preload")
        sid = self._next_uid
        self._next_uid += 1
        self._parked[sid] = r
        self._parked_slots.add(r)
        self.stats["preloads"] += 1
        return sid

    def can_preload(self, prompt_len=None) -> bool:
        del prompt_len
        if self._free_slot() is not None:
            return True
        queued = {q.session for q in self.queue if q.session is not None}
        queued |= {q.prefix for q in self.queue if q.prefix is not None}
        return any(sid not in queued for sid in self._parked)

    def release(self, sid: int) -> bool:
        r = self._parked.pop(sid, None)
        if r is None:
            return False
        self._parked_slots.discard(r)
        return True

    def cancel(self, uid: int) -> bool:
        for i, q in enumerate(self.queue):
            if q.uid == uid:
                del self.queue[i]
                return True
        for r in range(self.slots):
            if self._req[r] is not None and self._req[r].uid == uid:
                self._req[r] = None
                return True
        return False

    # --------------------------------------------------------- accounting
    @property
    def active_slots(self) -> list[int]:
        return [r for r in range(self.slots) if self._req[r] is not None]

    def active_uids(self) -> list[int]:
        return [self._req[r].uid for r in self.active_slots]

    def slot_accounting(self) -> dict:
        active = len(self.active_slots)
        parked = len(self._parked_slots)
        return {"slots": self.slots, "active": active, "parked": parked,
                "free": self.slots - active - parked,
                "queued": len(self.queue)}

    def new_tokens_since(self, seen: dict[int, int]) -> dict[int, list[int]]:
        out: dict[int, list[int]] = {}
        for r in self.active_slots:
            uid = self._req[r].uid
            n = seen.get(uid)
            if n is not None and len(self._generated[r]) > n:
                out[uid] = self._generated[r][n:]
        return out

    # ---------------------------------------------------------- scheduler
    def _free_slot(self) -> int | None:
        for r in range(self.slots):
            if self._req[r] is None and r not in self._parked_slots:
                return r
        return None

    def _token(self, req: Request, n: int) -> int:
        return (sum(req.prompt) + 13 * req.uid + n) % self.vocab

    def _start(self, r: int, req: Request) -> Completion | None:
        self._req[r] = req
        self._generated[r] = [self._token(req, 0)]
        self.stats["prefills"] += 1
        self.stats["generated_tokens"] += 1
        return self._maybe_finish(r)

    def _maybe_finish(self, r: int) -> Completion | None:
        req = self._req[r]
        gen = self._generated[r]
        done_eos = req.eos_id is not None and gen[-1] == req.eos_id
        if not done_eos and len(gen) < req.max_new_tokens:
            return None
        self._req[r] = None
        session = None
        if req.keep:
            session = req.uid
            self._parked[session] = r
            self._parked_slots.add(r)
        return Completion(req.uid, req.prompt, gen,
                          "eos" if done_eos else "length",
                          session=session,
                          logprobs=[-0.5] * len(gen))

    def step(self) -> list[Completion]:
        finished: list[Completion] = []
        t0 = time.perf_counter()
        while self.queue:
            req = self.queue[0]
            if req.session is not None:
                r = self._parked.pop(req.session, None)
                if r is None:
                    self.queue.popleft()
                    finished.append(Completion(req.uid, req.prompt, [],
                                               "session_evicted"))
                    continue
                self._parked_slots.discard(r)
                self.stats["resumes"] += 1
            else:
                r = self._free_slot()
                if r is None:
                    break
                if req.prefix is not None:
                    if req.prefix not in self._parked:
                        self.queue.popleft()
                        finished.append(Completion(
                            req.uid, req.prompt, [], "session_evicted"))
                        continue
                    self.stats["forks"] += 1
            self.queue.popleft()
            done = self._start(r, req)
            if done is not None:
                finished.append(done)
        self.stats["admit_ms"] += (time.perf_counter() - t0) * 1e3
        active = self.active_slots
        if not active:
            return finished
        t_dev = time.perf_counter()
        if self.step_delay_s:
            time.sleep(self.step_delay_s)
        for r in active:
            if self._req[r] is None:
                continue
            self._generated[r].append(
                self._token(self._req[r], len(self._generated[r])))
            self.stats["generated_tokens"] += 1
            done = self._maybe_finish(r)
            if done is not None:
                finished.append(done)
        self.stats["steps"] += 1
        self.stats["device_ms"] += (time.perf_counter() - t_dev) * 1e3
        return finished

    def run(self):
        while self.queue or self.active_slots:
            yield from self.step()


class FakeCaptureBackend:
    """Managed-profiler backend that records window open/close by
    writing a marker file — enough for the acceptance drill to assert
    "a capture fired" from a subprocess (PDTT_PROFILE_BACKEND=fake)."""

    def __init__(self):
        self.dirs: list[str] = []
        self._open: str | None = None

    def start(self, logdir: str) -> None:
        import os

        os.makedirs(logdir, exist_ok=True)
        self._open = logdir
        self.dirs.append(logdir)

    def stop(self) -> None:
        import os

        if self._open is not None:
            with open(os.path.join(self._open, "FAKE_CAPTURE"), "w") as f:
                f.write("ok\n")
            self._open = None
