"""Multi-replica routing core: health, balancing, retry, hedging.

The fault-tolerance story serving needs but a single process cannot
give: a thin front over N serve_http replicas that keeps answering
while individual replicas die, drain, or straggle. The HTTP surface
lives in ``tools/serve_router.py``; this module is the logic so tests
drive it in-process:

- **ReplicaSet** — the routable world: per-replica state
  (``up | draining | down``), outstanding-request counts (the
  balancing signal), and the last /healthz body (queue depth,
  admission state — so a ``shedding`` replica stops receiving work
  before its clients ever see a 429).
- **HealthProber** — background /healthz probes; state flips are
  journaled (``serve``/``replica_down`` / ``replica_up``) so an outage
  reads out of the same cross-host timeline as everything else.
- **Router** — pick the up replica with the fewest outstanding
  requests; RETRY idempotent requests on connect failure or a
  retryable status (a dead or draining replica costs a failover, not
  an error); optionally HEDGE a straggling completion onto a second
  replica after a latency-percentile delay (first answer wins);
  ``rolling_restart`` walks every replica through serve_http's
  existing drain path one at a time.

Every request carries a distributed trace context (obs/tracing.py):
the router stamps (or honors an inbound ``traceparent``) and each
attempt — retry, failover, hedge — is a child span whose context rides
the wire to the replica; hedge copies are sent pre-sampled so the
winner's replica retains its subtree even though it is fast and
healthy. Retention is decided tail-based at request end.

Idempotency rule: a request is retried/hedged only when re-executing
it cannot duplicate side effects — plain completions (and ``n``/chat
ones). ``keep``/``session``/``prefix`` requests mutate replica-local
KV state, are pinned to the replica that owns the session, and never
retry; streams retry only before the first relayed byte (the HTTP
front's job).
"""

from __future__ import annotations

import dataclasses
import json
import queue as queue_mod
import threading
import time
import urllib.error
import urllib.request
from collections import deque

from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs import spans as spans_lib
from pytorch_distributed_train_tpu.obs import tracing
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.obs.spans import span
from pytorch_distributed_train_tpu.serving_plane.slo import percentile

# statuses a healthy twin could serve better: shed (429), gateway-ish
# (502), draining / scheduler-dead (503)
RETRYABLE_STATUSES = (429, 502, 503)


def http_json(addr: str, path: str, body: bytes | None,
              timeout: float,
              headers: dict | None = None) -> tuple[int, bytes]:
    """One HTTP exchange with a replica. Returns (status, body) for ANY
    HTTP status (error statuses are routing inputs here, not
    exceptions); raises OSError only for connect/transport failure."""
    hdrs = {"Content-Type": "application/json"} if body else {}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(
        f"http://{addr}{path}", data=body, headers=hdrs,
        method="POST" if body is not None else "GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except urllib.error.URLError as e:
        raise OSError(str(e.reason)) from e


@dataclasses.dataclass
class Replica:
    addr: str
    state: str = "up"            # up | draining | down
    outstanding: int = 0
    fails: int = 0               # consecutive probe failures
    healthz: dict = dataclasses.field(default_factory=dict)
    # dispatch weight (fleet-controller rebalance hook): balancing
    # divides effective load by it, so a 0.5-weight replica carries
    # half the traffic of a 1.0 one at equal outstanding counts
    weight: float = 1.0
    # role-aware dispatch stub (ROADMAP item 2 — prefill/decode
    # pools): "mixed" replicas serve everything; pick(role=) prefers a
    # matching pool when one exists and falls back to mixed otherwise
    role: str = "mixed"


class ReplicaSet:
    def __init__(self, addrs: tuple[str, ...] = ()):
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        for a in addrs:
            self.add(a)

    def add(self, addr: str, role: str = "mixed") -> None:
        with self._lock:
            if addr not in self._replicas:
                self._replicas[addr] = Replica(addr, role=role)

    def set_weights(self, weights: dict) -> None:
        """Apply dispatch weights (addr → positive float; missing
        addrs keep their current weight). The fleet controller's
        rebalance actuator lands here — and through serve_router's
        ``POST /admin/weights``."""
        with self._lock:
            for addr, w in weights.items():
                r = self._replicas.get(addr)
                if r is None:
                    continue
                try:
                    w = float(w)
                except (TypeError, ValueError):
                    continue
                if w > 0.0:
                    r.weight = w

    def addrs(self) -> list[str]:
        with self._lock:
            return list(self._replicas)

    def get(self, addr: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(addr)

    def mark(self, addr: str, state: str, healthz: dict | None = None,
             fails: int | None = None) -> None:
        """Set a replica's state; up<->down/draining flips are
        journaled — the router's view of an outage belongs in the same
        timeline as the replica's own drain events."""
        with self._lock:
            r = self._replicas.get(addr)
            if r is None:
                return
            prev = r.state
            r.state = state
            if healthz is not None:
                r.healthz = healthz
            if fails is not None:
                r.fails = fails
        if prev != state:
            events_lib.emit(
                "serve",
                "replica_up" if state == "up" else "replica_down",
                addr=addr, prev=prev, state=state)
            get_registry().counter(
                "serve_replica_flips_total", labels={"state": state},
                help="router-observed replica state changes").inc()

    def note_fail(self, addr: str) -> int:
        """Bump and return a replica's consecutive probe-failure count
        (the prober's down_after debounce)."""
        with self._lock:
            r = self._replicas.get(addr)
            if r is None:
                return 0
            r.fails += 1
            return r.fails

    def begin(self, addr: str) -> None:
        with self._lock:
            r = self._replicas.get(addr)
            if r is not None:
                r.outstanding += 1

    def end(self, addr: str) -> None:
        with self._lock:
            r = self._replicas.get(addr)
            if r is not None:
                r.outstanding = max(0, r.outstanding - 1)

    def pick(self, exclude: set[str] = frozenset(),
             role: str | None = None) -> str | None:
        """Least-loaded routable replica, where load is outstanding
        requests divided by the dispatch weight (rebalance hook). A
        replica whose own admission state says ``shedding`` ranks
        after every non-shedding one — the router backs off before the
        429s start. ``role`` prefers a matching pool when one exists
        (prefill/decode split, ROADMAP item 2) and falls back to the
        whole up set otherwise."""
        with self._lock:
            cands = [r for r in self._replicas.values()
                     if r.state == "up" and r.addr not in exclude]
            if role is not None:
                pool = [r for r in cands if r.role == role]
                if pool:
                    cands = pool
            if not cands:
                return None
            return min(
                cands,
                key=lambda r: (r.healthz.get("admission") == "shedding",
                               (r.outstanding + 1) / max(r.weight, 1e-9),
                               r.addr)).addr

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [{"addr": r.addr, "state": r.state,
                     "outstanding": r.outstanding,
                     "weight": r.weight, "role": r.role,
                     "admission": r.healthz.get("admission"),
                     "queue_depth": r.healthz.get("queue_depth")}
                    for r in self._replicas.values()]


class HealthProber:
    """Background /healthz probing. 200 → up; 503 whose body says
    ``draining`` → draining (routable never, but expected back); any
    other 5xx body → down; ``down_after`` consecutive connect failures
    → down (one lost packet must not evict a replica)."""

    def __init__(self, replicas: ReplicaSet, *, interval_s: float = 0.5,
                 down_after: int = 2, timeout_s: float = 2.0,
                 fetch=None, refresh=None):
        self.replicas = replicas
        self.interval_s = interval_s
        self.down_after = max(1, down_after)
        self.timeout_s = timeout_s
        self._fetch = fetch or self._http_fetch
        # optional discovery hook (elastic.discover_replicas): called
        # each round so replicas advertised after router start join the
        # routable set without a restart
        self._refresh = refresh
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _http_fetch(self, addr: str) -> tuple[int, dict]:
        status, body = http_json(addr, "/healthz", None, self.timeout_s)
        try:
            return status, json.loads(body)
        except ValueError:
            return status, {}

    def probe_once(self) -> None:
        if self._refresh is not None:
            try:
                for addr in self._refresh():
                    self.replicas.add(addr)
            except Exception:
                pass  # discovery store flaked: probe what we have
        for addr in self.replicas.addrs():
            try:
                status, health = self._fetch(addr)
            except OSError:
                if self.replicas.note_fail(addr) >= self.down_after:
                    self.replicas.mark(addr, "down")
                continue
            flat = dict(health)
            flat.setdefault("admission",
                            (health.get("reliability") or {}).get(
                                "admission"))
            flat.setdefault("queue_depth",
                            (health.get("reliability") or {}).get(
                                "queue_depth"))
            if status == 200:
                self.replicas.mark(addr, "up", healthz=flat, fails=0)
            elif health.get("status") == "draining":
                self.replicas.mark(addr, "draining", healthz=flat,
                                   fails=0)
            else:
                self.replicas.mark(addr, "down", healthz=flat, fails=0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception as e:  # noqa: BLE001 — the prober must live
                print(f"[router] probe error {type(e).__name__}: {e}",
                      flush=True)

    def start(self) -> None:
        self.probe_once()  # synchronous first pass: route immediately
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="router-health-prober")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class Router:
    def __init__(self, replicas: ReplicaSet, *, timeout_s: float = 600.0,
                 hedge_after_s: float = 0.0, hedge_pct: float = 0.0,
                 hedge_min_s: float = 0.05, lat_window: int = 256,
                 sessions_max: int = 4096):
        self.replicas = replicas
        self.timeout_s = timeout_s
        self.hedge_after_s = hedge_after_s
        self.hedge_pct = hedge_pct
        self.hedge_min_s = hedge_min_s
        self._lat: deque[float] = deque(maxlen=lat_window)
        self._lat_lock = threading.Lock()
        # session id -> owning replica (sessions are replica-local KV);
        # insertion-ordered and bounded — see note_session. Read and
        # mutated from concurrent handler threads: all access under
        # _sessions_lock (an unlocked eviction loop races next(iter())
        # against concurrent inserts/pops).
        self.sessions: dict[int, str] = {}
        self.sessions_max = sessions_max
        self._sessions_lock = threading.Lock()

    # ------------------------------------------------------------- policy
    def classify(self, body: dict) -> tuple[str | None, bool]:
        """(pinned_addr, idempotent) for a request body. Session-bound
        requests go to the replica that parked the session and never
        retry; everything else is fair game."""
        sid = body.get("session", body.get("prefix"))
        if sid is not None:
            try:
                sid = int(sid)
            except (TypeError, ValueError):
                # malformed session id: forward unpinned so the replica
                # answers its documented 400 (the router must not crash
                # on client input)
                return None, False
            with self._sessions_lock:
                return self.sessions.get(sid), False
        return None, not body.get("keep", False)

    def note_session(self, response_body: bytes, addr: str) -> None:
        """Record session ownership from a completed response so later
        ``session=``/``prefix=`` turns route home. The map is bounded
        (oldest entries evicted — replicas LRU-evict their parked
        sessions under pressure anyway, so an evicted mapping matches a
        session that was about to die server-side too)."""
        try:
            sid = json.loads(response_body).get("session")
        except (ValueError, AttributeError):
            return
        if sid is None:
            return
        with self._sessions_lock:
            self.sessions[int(sid)] = addr
            while len(self.sessions) > self.sessions_max:
                self.sessions.pop(next(iter(self.sessions)))

    def hedge_delay(self) -> float | None:
        """Delay before a second copy goes out: the configured
        percentile of recent request latencies (floored), or the fixed
        knob. None = hedging off."""
        if self.hedge_pct > 0:
            with self._lat_lock:
                xs = sorted(self._lat)
            if len(xs) >= 8:
                return max(self.hedge_min_s,
                           percentile(xs, self.hedge_pct))
            return None  # not enough signal yet
        return self.hedge_after_s if self.hedge_after_s > 0 else None

    # ------------------------------------------------------------ request
    def _single(self, addr: str, path: str, body: bytes,
                out: queue_mod.Queue, parent=None, sampled: bool = False,
                hedge: bool = False) -> None:
        """One attempt against one replica. ``parent`` is the request's
        root :class:`tracing.TraceContext`: the attempt becomes a child
        span of it and the upstream replica continues the trace through
        a ``traceparent`` header (``sampled`` set = the replica must
        retain its subtree — how a hedge's winner gets kept even though
        it is fast and healthy)."""
        self.replicas.begin(addr)
        t0 = time.monotonic()

        def _do(headers):
            try:
                return ("ok", *http_json(addr, path, body, self.timeout_s,
                                         headers=headers))
            except OSError as e:
                return ("conn_fail", 0, str(e).encode())

        try:
            if parent is not None:
                with spans_lib.trace_scope(parent.trace_id,
                                           parent.span_id), \
                        span("router.attempt", addr=addr, hedge=hedge):
                    child = tracing.current_child_context(sampled=sampled)
                    kind, status, rbody = _do(
                        {"traceparent": tracing.format_traceparent(child)}
                        if child is not None else None)
            else:
                kind, status, rbody = _do(None)
        finally:
            self.replicas.end(addr)
        if kind == "conn_fail":
            out.put((addr, "conn_fail", 0, rbody))
            return
        if status in RETRYABLE_STATUSES:
            out.put((addr, "retryable", status, rbody))
            return
        with self._lat_lock:
            self._lat.append(time.monotonic() - t0)
        out.put((addr, "ok", status, rbody))

    def request(self, path: str, body_bytes: bytes, body: dict,
                traceparent: str | None = None) -> tuple[int, bytes]:
        """Route one non-streaming POST. Returns (status, body). Stamps
        (or honors, via ``traceparent``) a distributed trace context;
        every attempt — retries, failovers, hedges — is a child span,
        and the tail sampler decides retention when the request ends."""
        ctx = tracing.continue_or_start(traceparent)
        t0 = time.monotonic()
        with tracing.activate(ctx):
            with span("router.request", path=path):
                root = tracing.current_child_context(sampled=ctx.sampled)
                status, rbody = self._route(path, body_bytes, body, root)
        tracer = tracing.get_tracer()
        if status == 504:
            tracer.flag(ctx.trace_id, "deadline")
        elif status == 429:
            tracer.flag(ctx.trace_id, "shed")
        elif status >= 500:
            tracer.flag(ctx.trace_id, "error")
        tracer.finish(ctx.trace_id, dur_s=time.monotonic() - t0)
        return status, rbody

    def _route(self, path: str, body_bytes: bytes, body: dict,
               root) -> tuple[int, bytes]:
        pinned, idempotent = self.classify(body)
        if pinned is not None:
            rep = self.replicas.get(pinned)
            if rep is None or rep.state != "up":
                return 503, json.dumps(
                    {"error": f"session replica {pinned} unavailable"}
                ).encode()
            out: queue_mod.Queue = queue_mod.Queue()
            self._single(pinned, path, body_bytes, out, parent=root,
                         sampled=root.sampled if root else False)
            _, kind, status, rbody = out.get()
            if kind == "conn_fail":
                return 502, json.dumps(
                    {"error": "session replica unreachable"}).encode()
            if kind == "ok":
                # a kept resume consumes the session and parks a NEW
                # one: learn the fresh id here too, or the chain's next
                # turn routes unpinned to an arbitrary replica
                self.note_session(rbody, pinned)
            return status, rbody
        tried: set[str] = set()
        last: tuple[int, bytes] | None = None
        attempt = 0
        while True:
            addr = self.replicas.pick(exclude=tried)
            if addr is None:
                if last is not None:
                    return last
                return 503, json.dumps(
                    {"error": "no replica available"}).encode()
            tried.add(addr)
            # after a failed first attempt every further hop is an
            # incident path: force downstream retention so the whole
            # failover story is reconstructable
            sampled = (root.sampled if root else False) or attempt > 0
            result = self._attempt_hedged(addr, path, body_bytes, tried,
                                          hedge=idempotent, parent=root,
                                          sampled=sampled)
            attempt += 1
            a, kind, status, rbody = result
            if kind == "ok":
                if not idempotent:
                    self.note_session(rbody, a)
                return status, rbody
            if not idempotent:
                # non-idempotent requests never re-execute: surface the
                # transport/retryable failure honestly
                return (status or 502), rbody
            if root is not None:
                tracing.flag(root.trace_id, "failover")
            events_lib.emit("serve", "failover", addr=a, path=path,
                            reason=kind, status=status)
            get_registry().counter(
                "serve_failovers_total",
                help="requests retried on another replica").inc()
            last = ((status or 502), rbody)

    def _attempt_hedged(self, addr: str, path: str, body_bytes: bytes,
                        tried: set[str], hedge: bool, parent=None,
                        sampled: bool = False):
        """One attempt with optional hedging: fire ``addr``, and if no
        answer lands within the hedge delay, fire a second copy at the
        next-best replica; first completed answer wins (an 'ok' beats a
        pending primary; a hedged replica that also fails leaves the
        failover loop to continue)."""
        out: queue_mod.Queue = queue_mod.Queue()
        threading.Thread(target=self._single,
                         args=(addr, path, body_bytes, out),
                         kwargs={"parent": parent, "sampled": sampled},
                         daemon=True).start()
        delay = self.hedge_delay() if hedge else None
        hedged_addr = None
        if delay is not None:
            try:
                return out.get(timeout=delay)
            except queue_mod.Empty:
                hedged_addr = self.replicas.pick(exclude=tried | {addr})
            if hedged_addr is not None:
                if parent is not None:
                    # a hedged request is a tail by definition: retain
                    # the whole tree here AND on the hedge's replica
                    # (sampled=True below rides the wire to it)
                    tracing.flag(parent.trace_id, "hedged")
                events_lib.emit("serve", "hedge", slow=addr,
                                hedge=hedged_addr, path=path,
                                after_s=round(delay, 4))
                get_registry().counter(
                    "serve_hedges_total",
                    help="straggler completions hedged onto a second "
                         "replica").inc()
                threading.Thread(
                    target=self._single,
                    args=(hedged_addr, path, body_bytes, out),
                    kwargs={"parent": parent, "sampled": True,
                            "hedge": True},
                    daemon=True).start()
        results = []
        expect = 1 + (1 if hedged_addr is not None else 0)
        for _ in range(expect):
            r = out.get()
            if r[1] == "ok":
                if hedged_addr is not None:
                    events_lib.emit("serve", "hedge_win", addr=r[0],
                                    path=path)
                    tried.add(hedged_addr)
                return r
            results.append(r)
        if hedged_addr is not None:
            tried.add(hedged_addr)
        return results[0]

    # ----------------------------------------------------- rolling restart
    def rolling_restart(self, *, drain_path: str = "/admin/drain",
                        poll_s: float = 0.2, down_timeout_s: float = 30.0,
                        wait_back_s: float = 60.0) -> list[dict]:
        """Walk every replica through serve_http's drain path, one at a
        time: stop routing to it, POST the drain, wait for it to leave
        (its supervisor restarts it), and wait for it to come BACK
        (``wait_back_s`` — on by default: draining the next replica
        while the previous one is still down would take a 2-replica
        fleet fully offline, exactly what a rolling restart exists to
        avoid; after the timeout the walk proceeds anyway so a dead
        supervisor degrades the restart instead of wedging it) —
        in-flight requests finish, new ones land on the others, so a
        fleet-wide restart costs zero failed requests."""
        report = []
        for addr in list(self.replicas.addrs()):
            rep = self.replicas.get(addr)
            if rep is None or rep.state == "down":
                report.append({"addr": addr, "skipped": "down"})
                continue
            events_lib.emit("serve", "rolling_drain", addr=addr)
            self.replicas.mark(addr, "draining")
            try:
                http_json(addr, drain_path, b"{}", 5.0)
            except OSError:
                pass  # already gone: counts as drained
            deadline = time.monotonic() + down_timeout_s
            while time.monotonic() < deadline:
                try:
                    http_json(addr, "/healthz", None, 1.0)
                except OSError:
                    break  # exited: drained
                time.sleep(poll_s)  # still draining in-flight work
            self.replicas.mark(addr, "down")
            entry = {"addr": addr, "drained": True}
            if wait_back_s > 0:
                back_by = time.monotonic() + wait_back_s
                while time.monotonic() < back_by:
                    try:
                        status, _ = http_json(addr, "/healthz", None, 1.0)
                    except OSError:
                        time.sleep(poll_s)
                        continue
                    if status == 200:
                        self.replicas.mark(addr, "up")
                        entry["back"] = True
                        break
                    time.sleep(poll_s)
            report.append(entry)
        return report

    def weight_sync(self, *, version: int | None = None,
                    traceparent: str | None = None,
                    timeout_s: float = 60.0) -> list[dict]:
        """Broadcast a live weight swap to every routable replica
        (serve_http's ``POST /admin/weights``) — the online loop's
        one-call "swap the fleet" (docs/online_training.md).

        Sequential on purpose: at most one replica pays its swap pause
        at a time, so fleet capacity never dips by more than one
        replica's worth — the weight-plane analogue of the rolling
        restart above. Per-replica failures land in the report (the
        caller retries laggards next cycle); they never abort the walk.
        """
        body = json.dumps(
            {} if version is None else {"version": int(version)}).encode()
        headers = ({"traceparent": traceparent} if traceparent else None)
        report: list[dict] = []
        for addr in list(self.replicas.addrs()):
            rep = self.replicas.get(addr)
            if rep is None or rep.state == "down":
                report.append({"addr": addr, "skipped": "down"})
                continue
            try:
                status, raw = http_json(addr, "/admin/weights", body,
                                        timeout_s, headers=headers)
            except OSError as e:
                report.append({"addr": addr,
                               "error": f"{type(e).__name__}: {e}"})
                continue
            entry = {"addr": addr, "http_status": status}
            try:
                out = json.loads(raw)
                if isinstance(out, dict):
                    entry.update(out)
            except ValueError:
                entry["error"] = "non-json swap response"
            report.append(entry)
        swapped = sum(1 for e in report if e.get("status") == "swapped")
        events_lib.emit("weights", "fleet_sync",
                        version=(int(version) if version is not None
                                 else "latest"),
                        replicas=len(report), swapped=swapped)
        return report
