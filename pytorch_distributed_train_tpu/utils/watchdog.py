"""In-job failure detection: heartbeat monitor + flight recorder
(SURVEY §5.3a, C25/C26).

Reference machinery being replaced: ProcessGroupNCCL's watchdog thread +
HeartbeatMonitor (ProcessGroupNCCL.hpp:562,592 — dump debug state and abort
when collectives wedge) and the c10d FlightRecorder ring buffer of recent
collectives (FlightRecorder.hpp:98).

TPU analogue: the failure mode is a stalled step (wedged DCN link, hung
host), not a divergent collective (SPMD can't author those — SURVEY §5.2).
So:
- ``FlightRecorder`` — fixed-size ring of recent step events (host-side,
  lock-free enough: GIL-atomic list assignment), dumped to stderr + file on
  abort or SIGTERM/SIGQUIT.
- ``Heartbeat`` — daemon thread; if no step-end beat arrives within
  ``timeout_s``, dumps the ring + all-thread stacks and hard-aborts the
  process so the scheduler can restart the job (whole-job restart + Orbax
  auto-resume is the recovery path, SURVEY §5.3b).
"""

from __future__ import annotations

import faulthandler
import os
import signal
import sys
import threading
import time


def _graceful_preemption_armed() -> bool:
    """Is a graceful-preemption handler (faults/preemption.py) armed?
    Read via sys.modules so merely asking never imports the faults
    package — if it was never imported, nobody armed it."""
    mod = sys.modules.get("pytorch_distributed_train_tpu.faults.preemption")
    try:
        return bool(mod and mod.armed())
    except Exception:
        return False


class FlightRecorder:
    def __init__(self, capacity: int = 256, dump_dir: str = ""):
        self.capacity = capacity
        self.buf: list[tuple] = [None] * capacity  # type: ignore[list-item]
        self.n = 0
        self.dump_dir = dump_dir
        self._installed = False
        self._spans = None  # obs.spans.SpanRecorder, via attach_spans

    def attach_spans(self, recorder) -> None:
        """Dump this span ring (obs/spans.py) next to the event ring on
        abort/SIGTERM — "which step" from the events, "doing WHAT inside
        the step" from the spans (the wedged checkpoint save or input
        wait is then in the post-mortem, not inferred)."""
        self._spans = recorder

    def record(self, kind: str, step: int, **info) -> None:
        self.buf[self.n % self.capacity] = (time.time(), kind, step, info)
        self.n += 1

    def events(self) -> list[tuple]:
        if self.n <= self.capacity:
            return [e for e in self.buf[: self.n]]
        i = self.n % self.capacity
        return [e for e in self.buf[i:] + self.buf[:i]]

    def _write(self, out, reason: str = "") -> None:
        if reason:
            # Who ordered this dump and why — the cluster-wide hang dump
            # (sentinel/liveness.py) names the blamed host here, so a
            # post-mortem reading ONE file knows whether this host was
            # the wedged one or a bystander dumped for context.
            out.write(f"=== dump reason: {reason} ===\n")
        out.write(f"=== flight recorder: last {min(self.n, self.capacity)} events ===\n")
        for ts, kind, step, info in self.events():
            out.write(f"{ts:.3f} {kind} step={step} {info}\n")
        out.flush()
        if self._spans is not None:
            try:
                self._spans.write_text(out)
                # all threads' stacks, not active(): the heartbeat-abort
                # dump runs on the monitor thread, and the wedged span
                # (a stuck checkpoint.save) is open on the MAIN thread
                for t, names in self._spans.active_all().items():
                    out.write(f"open spans [{t}]: {names}\n")
                out.flush()
            except Exception:
                pass  # diagnostics must never crash the dump path

    def dump(self, out=None, reason: str = "", suffix: str = "") -> None:
        """``suffix`` distinguishes dump FILES with different causes in
        one process (the cluster hang dump must survive the SIGTERM
        teardown dump that follows it — same pid, same default path,
        mode "w" would clobber it)."""
        self._write(out or sys.stderr, reason)
        if self.dump_dir and out is None:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(self.dump_dir,
                                    f"flight_{os.getpid()}{suffix}.log")
                with open(path, "w") as f:
                    self._write(f, reason)
            except OSError:
                pass  # diagnostics must never crash the dump path

    def install_signal_dump(self) -> None:
        """Dump ring + stacks on SIGTERM (scheduler preemption) — the
        analogue of the NCCL watchdog's debug dump on timeout.

        Chains to any previously-installed handler instead of
        overwriting it, and leaves process exit to the train loop when a
        graceful-preemption handler is armed (faults/preemption.py) —
        the two compose in either install order. Only with no other
        handler in play does the legacy terminal ``sys.exit(143)`` run."""
        if self._installed:
            return
        self._installed = True
        faulthandler.enable()

        def _handler(signum, frame):
            self.dump()
            faulthandler.dump_traceback()
            if signum == signal.SIGINT:
                signal.default_int_handler(signum, frame)
                return
            if callable(prev) and prev not in (signal.SIG_DFL,
                                               signal.SIG_IGN):
                prev(signum, frame)  # chain first (it may raise/exit)
            if _graceful_preemption_armed():
                return  # the train loop checkpoints and exits cleanly
            # No graceful handler armed: keep the legacy guarantee that
            # SIGTERM terminates (fit()'s finally saves on the way down)
            # even when some OTHER chained handler returned — otherwise
            # the job trains through its grace window and gets SIGKILLed
            # with nothing saved.
            sys.exit(143)

        try:
            prev = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, _handler)
        except ValueError:
            pass  # not the main thread (tests)


class Heartbeat:
    """Abort-on-stall monitor. `beat()` after every step; a missing beat for
    `timeout_s` means the step wedged — dump and abort (exit code 134)."""

    def __init__(self, timeout_s: float, recorder: FlightRecorder | None = None,
                 abort=None):
        self.timeout_s = timeout_s
        self.recorder = recorder
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._abort = abort or self._default_abort
        self._thread: threading.Thread | None = None
        if timeout_s > 0:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="heartbeat-monitor")
            self._thread.start()

    def beat(self) -> None:
        self._last = time.monotonic()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(min(self.timeout_s / 4, 10.0)):
            if time.monotonic() - self._last > self.timeout_s:
                # Diagnostics are best-effort: a broken stderr (no fileno
                # under capture/redirection, closed pipe) must never keep
                # the abort from firing — failing open here means a wedged
                # job never gets restarted.
                try:
                    sys.stderr.write(
                        f"[heartbeat] no step completed in "
                        f"{self.timeout_s}s — aborting\n")
                    if self.recorder is not None:
                        self.recorder.dump()
                    faulthandler.dump_traceback()
                except Exception:
                    pass
                self._abort()
                return

    @staticmethod
    def _default_abort() -> None:
        os._exit(134)
