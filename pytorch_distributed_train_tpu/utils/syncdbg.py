"""tsan-lite runtime concurrency sanitizer (``PDTT_SANITIZE=1``).

The dynamic half of the concurrency correctness plane: the static
``lock-order`` / ``thread-lifecycle`` passes (tools/analyze/) prove
what they can see; this module watches the locks the program *actually
takes*. Drop-in instrumented ``Lock``/``RLock``/``Condition``/
``Thread`` replace the ``threading`` factories while active, and:

- maintain the **runtime lock-order graph** keyed by lock *creation
  site* (``path:line`` — the same identity the static pass uses, so
  ``python -m tools.analyze --only lock-order --compare-runtime g.json``
  can diff the two and name the static pass's blind spots);
- flag a **lock-order inversion the moment the second edge direction
  appears** — before any real deadlock needs the losing interleaving
  (``lock_inversion``);
- flag **blocking while holding a lock** longer than
  ``PDTT_SANITIZE_BLOCK_S`` — a slow acquire, ``Condition.wait`` on
  another lock, or ``Thread.join`` under a lock stalls every thread
  behind the held lock (``hold_while_blocking``);
- at teardown (``check_teardown()``, also an ``atexit`` hook) flag
  **non-daemon threads that were started but never joined**
  (``unjoined_thread``);
- run a **deadlock watchdog**: any thread stuck in an instrumented
  acquire for ``PDTT_SANITIZE_DEADLOCK_S`` gets every thread's stack
  dumped plus the wait-for cycle (who holds what, who waits for whom)
  named (``deadlock``).

Findings are printed, counted (``sanitizer_findings_total{kind=}``)
and journaled under the ``sanitizer`` event category; ``findings()``
returns them for asserts; soak tools exit nonzero on any. No jax
imports, obs imported lazily — the elastic agent and data workers can
activate this without touching a device backend.

Knobs (env): ``PDTT_SANITIZE=1`` activates (tests/conftest.py and the
tool entry points call :func:`maybe_activate`); ``PDTT_SANITIZE_BLOCK_S``
(default 1.0) is the blocking-while-holding threshold;
``PDTT_SANITIZE_DEADLOCK_S`` (default 20.0) the watchdog trip;
``PDTT_SANITIZE_GRAPH`` a path to auto-dump the runtime graph to at
exit.

Known limit: identity is the creation site, so two *instances* born on
one line nesting in both orders read as a self-pair and are skipped —
instance-level AB/BA needs distinct sites to be named.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import traceback

# Originals, saved at import: wrappers and the sanitizer's own state
# must run on the REAL primitives whatever is patched later.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_THREAD = threading.Thread

FINDING_KINDS = ("lock_inversion", "hold_while_blocking",
                 "unjoined_thread", "deadlock")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


class Finding:
    __slots__ = ("kind", "message", "detail", "ts")

    def __init__(self, kind: str, message: str, detail: dict):
        self.kind = kind
        self.message = message
        self.detail = detail
        self.ts = time.time()

    def as_dict(self) -> dict:
        return {"kind": self.kind, "message": self.message,
                "detail": self.detail, "ts": self.ts}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Finding({self.kind}: {self.message})"


class _State:
    def __init__(self):
        self.lock = _REAL_RLOCK()
        self.edges: dict[tuple[str, str], dict] = {}
        self.findings: list[Finding] = []
        self.threads: list = []              # live SanThread bookkeeping
        self.waiting: dict[int, tuple] = {}  # ident -> (lock, t0, held)
        self.owners: dict[int, int] = {}     # id(lock) -> owner ident
        self.reported_deadlocks: set[frozenset] = set()
        self.block_s = _env_f("PDTT_SANITIZE_BLOCK_S", 1.0)
        self.deadlock_s = _env_f("PDTT_SANITIZE_DEADLOCK_S", 20.0)
        self.watchdog_poll_s = 0.5
        self.watchdog = None
        self.epoch = 0     # bumps on (de)activate: retires old watchdogs


_state = _State()
_tls = threading.local()
_ACTIVE = False
_HOOKS_INSTALLED = False


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _suppressed() -> bool:
    return bool(getattr(_tls, "in_record", False))


def _rel(path: str) -> str:
    ap = os.path.abspath(path)
    if ap.startswith(_REPO_ROOT + os.sep):
        return ap[len(_REPO_ROOT) + 1:].replace(os.sep, "/")
    return path.replace(os.sep, "/")


def _creation_site() -> str:
    f = sys._getframe(1)
    while f is not None and f.f_globals.get("__name__", "") == __name__:
        f = f.f_back
    # threading internals (Event/Queue/Barrier building conditions and
    # locks through the patched factories) are not useful identities —
    # walk out to the first frame beyond the threading module too
    while f is not None and f.f_globals.get("__name__", "") == "threading":
        f = f.f_back
    if f is None:  # pragma: no cover
        return "<unknown>:0"
    return f"{_rel(f.f_code.co_filename)}:{f.f_lineno}"


def _short_stack(limit: int = 10) -> list[str]:
    out = []
    for line in traceback.format_stack()[:-2][-limit:]:
        out.append(line.strip().replace("\n", " | "))
    return out


def _record(kind: str, message: str, **detail) -> None:
    f = Finding(kind, message, detail)
    with _state.lock:
        _state.findings.append(f)
    if _suppressed():
        return
    _tls.in_record = True
    try:
        print(f"[syncdbg] {kind}: {message}", file=sys.stderr, flush=True)
        try:
            from pytorch_distributed_train_tpu.obs.registry import (
                get_registry,
            )

            get_registry().counter(
                "sanitizer_findings_total", labels={"kind": kind},
                help="runtime concurrency-sanitizer findings by "
                     "kind").inc()
        except Exception:
            pass
        try:
            from pytorch_distributed_train_tpu.obs import events as ev

            ev.emit("sanitizer", kind, message=message, **detail)
        except Exception:
            pass
    finally:
        _tls.in_record = False


# ------------------------------------------------------------- the graph
def _note_acquired(lock) -> None:
    """Edges held -> lock; inversion check the moment the second
    direction appears."""
    held = _held()
    if held and not _suppressed():
        me = threading.current_thread().name
        for h in held:
            a, b = h.site, lock.site
            if a == b:
                continue
            with _state.lock:
                fwd = _state.edges.get((a, b))
                rev = _state.edges.get((b, a))
                if fwd is None:
                    _state.edges[(a, b)] = fwd = {
                        "count": 0, "thread": me,
                        "stack": _short_stack()}
                fwd["count"] += 1
                inverted = rev is not None and not fwd.get("reported") \
                    and not rev.get("reported")
                if inverted:
                    fwd["reported"] = rev["reported"] = True
            if inverted:
                _record(
                    "lock_inversion",
                    f"lock order inverted: `{b}` was acquired while "
                    f"holding `{a}` (thread {me}), but `{a}` has been "
                    f"acquired while holding `{b}` (thread "
                    f"{rev['thread']}) — these two paths deadlock under "
                    f"the right interleaving",
                    edge=[a, b], reverse_stack=rev["stack"],
                    stack=_short_stack())
    with _state.lock:
        _state.owners[id(lock)] = threading.get_ident()
    held.append(lock)


def _note_released(lock) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            break
    if not any(h is lock for h in held):
        with _state.lock:
            _state.owners.pop(id(lock), None)


def _blocking_guard(what: str, lock, t0: float, waited: float) -> None:
    """hold_while_blocking: we just blocked `waited` seconds on `what`
    while other locks were held."""
    if _suppressed() or waited < _state.block_s:
        return
    others = [h.site for h in _held() if h is not lock]
    if not others:
        return
    _record(
        "hold_while_blocking",
        f"blocked {waited:.2f}s in {what} while holding "
        f"{', '.join('`%s`' % s for s in others)} — every thread behind "
        f"those locks stalled for the whole wait",
        what=what, waited_s=round(waited, 3), held=others,
        stack=_short_stack())


class _Waiting:
    """Context: this thread blocks on `lock` (watchdog visibility)."""

    def __init__(self, lock, what: str):
        self.lock = lock
        self.what = what
        self.t0 = time.monotonic()

    def __enter__(self):
        if not _suppressed():
            with _state.lock:
                _state.waiting[threading.get_ident()] = (
                    self.lock, self.t0, tuple(h.site for h in _held()),
                    self.what)
        return self

    def __exit__(self, *exc):
        with _state.lock:
            _state.waiting.pop(threading.get_ident(), None)
        return False


# ------------------------------------------------------------- wrappers
class _SanBase:
    _kind = "Lock"

    def __init__(self, real):
        self._real = real
        self.site = _creation_site()

    # threading.Condition support for wrapped locks
    def _release_save(self):
        _note_released(self)
        return self._real._release_save() if hasattr(
            self._real, "_release_save") else (self._real.release() or True)

    def _acquire_restore(self, state):
        if hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(state)
        else:
            self._real.acquire()
        _held().append(self)
        with _state.lock:
            _state.owners[id(self)] = threading.get_ident()

    def _is_owned(self):
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.monotonic()
        if blocking:
            with _Waiting(self, f"{self._kind}.acquire"):
                got = self._real.acquire(True, timeout)
        else:
            got = self._real.acquire(False)
        if got:
            waited = time.monotonic() - t0
            first = not any(h is self for h in _held())
            if first:
                _blocking_guard(f"{self._kind}.acquire", self, t0, waited)
                _note_acquired(self)
            else:
                _held().append(self)   # re-entrant: bookkeeping only
        return got

    def release(self):
        _note_released(self)
        self._real.release()

    def locked(self):
        return self._real.locked()

    def _at_fork_reinit(self):
        # stdlib contract (concurrent.futures registers it as an
        # at-fork hook): reinitialize the underlying primitive
        self._real._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<San{self._kind} site={self.site}>"


class SanLock(_SanBase):
    _kind = "Lock"


class SanRLock(_SanBase):
    _kind = "RLock"


def Lock():
    return SanLock(_REAL_LOCK())


def RLock():
    return SanRLock(_REAL_RLOCK())


class Condition:
    """Sanitized Condition: a real Condition over the (unwrapped) real
    lock, with held-stack bookkeeping on the wrapper. Entering the
    Condition acquires its lock — same stance as the static passes."""

    def __init__(self, lock=None):
        if lock is None:
            lock = SanRLock(_REAL_RLOCK())
            lock.site = _creation_site()
        elif not isinstance(lock, _SanBase):
            lock = SanLock(lock) if not hasattr(lock, "_release_save") \
                else SanRLock(lock)
            lock.site = _creation_site()
        self._san = lock
        self.site = lock.site
        self._cond = _REAL_CONDITION(lock._real)

    def acquire(self, *a, **kw):
        return self._san.acquire(*a, **kw)

    def release(self):
        self._san.release()

    def __enter__(self):
        self._san.acquire()
        return self

    def __exit__(self, *exc):
        self._san.release()
        return False

    def wait(self, timeout=None):
        # the real wait releases the lock: mirror that in the held
        # stack, and time the block — waiting on a condition while
        # holding ANOTHER lock is the hold_while_blocking pattern.
        # Ownership is pre-checked HERE so the un-acquired-lock
        # RuntimeError fires before any bookkeeping: the finally below
        # assumes the real wait released-then-reacquired (which CPython
        # guarantees even on interruption mid-wait, via its own
        # finally), and must not fabricate a held entry for a lock
        # this thread never owned.
        if not self._cond._is_owned():
            raise RuntimeError("cannot wait on un-acquired lock")
        t0 = time.monotonic()
        _note_released(self._san)
        try:
            with _Waiting(self._san, "Condition.wait"):
                return self._cond.wait(timeout)
        finally:
            _held().append(self._san)
            with _state.lock:
                _state.owners[id(self._san)] = threading.get_ident()
            _blocking_guard("Condition.wait", self._san, t0,
                            time.monotonic() - t0)

    def wait_for(self, predicate, timeout=None):
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    notifyAll = notify_all

    def _at_fork_reinit(self):
        self._san._at_fork_reinit()
        self._cond._at_fork_reinit()


class Thread(_REAL_THREAD):
    """Instrumented thread: records its creation site and whether it
    was ever joined, for the teardown unjoined-thread check; times
    joins performed while locks are held. Registration happens at
    ``start()`` — daemonness is final there — and only for non-daemon
    threads (daemons are exempt from the teardown check anyway), with
    deregistration on a completed join, so a long sanitized soak's
    thread churn cannot grow the registry without bound."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.san_site = _creation_site()
        self.san_joined = False

    def start(self):
        if not self.daemon:
            with _state.lock:
                _state.threads.append(self)
        super().start()

    def join(self, timeout=None):
        t0 = time.monotonic()
        with _Waiting(None, "Thread.join"):
            super().join(timeout)
        if not self.is_alive():
            self.san_joined = True
            with _state.lock:
                try:
                    _state.threads.remove(self)
                except ValueError:
                    pass
        if _held():
            _blocking_guard("Thread.join", None, t0,
                            time.monotonic() - t0)


# ------------------------------------------------------------- watchdog
def _dump_all_stacks(out) -> None:
    frames = sys._current_frames()
    for t in threading.enumerate():
        f = frames.get(t.ident)
        if f is None:
            continue
        print(f"--- thread {t.name} (ident {t.ident}, "
              f"daemon={t.daemon}) ---", file=out)
        for line in traceback.format_stack(f):
            print("  " + line.rstrip().replace("\n", "\n  "), file=out)


def _find_wait_cycle(start_ident: int):
    """Follow waiter -> held lock's owner -> their waited lock ... and
    return the lock-site cycle if it loops, else None."""
    with _state.lock:
        waiting = dict(_state.waiting)
        owners = dict(_state.owners)
    path_sites: list[str] = []
    seen: list[int] = []
    ident = start_ident
    while ident not in seen:
        seen.append(ident)
        entry = waiting.get(ident)
        if entry is None or entry[0] is None:
            return None
        lock = entry[0]
        path_sites.append(lock.site)
        ident = owners.get(id(lock))
        if ident is None:
            return None
        if ident == start_ident:
            return path_sites
    return None


def _watchdog_loop(epoch: int) -> None:
    # epoch-tagged: a deactivate→activate cycle within one poll must
    # retire THIS loop even though _ACTIVE reads true again — only the
    # newest epoch's watchdog survives
    while _ACTIVE and _state.epoch == epoch:
        time.sleep(_state.watchdog_poll_s)
        if not _ACTIVE or _state.epoch != epoch:
            return
        now = time.monotonic()
        stuck = []
        with _state.lock:
            for ident, entry in _state.waiting.items():
                # an idle consumer parked on its own condition holding
                # nothing is NORMAL (the persister between persists);
                # only hold-and-wait past the deadline is a hazard
                if now - entry[1] >= _state.deadlock_s and entry[2]:
                    stuck.append((ident, entry))
        if not stuck:
            continue
        idents = frozenset(i for i, _ in stuck)
        with _state.lock:
            if idents in _state.reported_deadlocks:
                continue
            _state.reported_deadlocks.add(idents)
        cycle = None
        for ident, _entry in stuck:
            cycle = _find_wait_cycle(ident)
            if cycle:
                break
        names = {t.ident: t.name for t in threading.enumerate()}
        waits = "; ".join(
            f"{names.get(i, i)} stuck {now - e[1]:.1f}s in {e[3]} on "
            f"`{e[0].site if e[0] is not None else '<thread>'}` "
            f"(holding {', '.join(e[2]) or 'nothing'})"
            for i, e in stuck)
        cyc = (" wait-for cycle: " + " -> ".join(cycle + [cycle[0]])
               if cycle else " (no closed cycle found — a hold-and-wait "
               "or a lost wakeup)")
        _record("deadlock",
                f"threads blocked past {_state.deadlock_s:.0f}s: {waits}."
                + cyc,
                cycle=cycle or [], waiters=sorted(names.get(i, str(i))
                                                  for i in idents))
        print("[syncdbg] all-thread stack dump follows", file=sys.stderr)
        _dump_all_stacks(sys.stderr)


# ------------------------------------------------------------- lifecycle
def activate(*, block_s: float | None = None,
             deadlock_s: float | None = None,
             watchdog_poll_s: float | None = None) -> None:
    """Patch threading's factories and start the watchdog. Idempotent."""
    global _ACTIVE
    if block_s is not None:
        _state.block_s = block_s
    if deadlock_s is not None:
        _state.deadlock_s = deadlock_s
    if watchdog_poll_s is not None:
        _state.watchdog_poll_s = watchdog_poll_s
    if _ACTIVE:
        return
    _ACTIVE = True
    _state.epoch += 1
    threading.Lock = Lock
    threading.RLock = RLock
    threading.Condition = Condition
    threading.Thread = Thread
    _state.watchdog = _REAL_THREAD(target=_watchdog_loop,
                                   args=(_state.epoch,), daemon=True,
                                   name="syncdbg-watchdog")
    _state.watchdog.start()
    global _HOOKS_INSTALLED
    if not _HOOKS_INSTALLED:
        _HOOKS_INSTALLED = True
        atexit.register(_atexit_hook)
        try:
            # forked workers (data/workers.py) inherit the parent's
            # state: its thread registry would read as "unjoined" at
            # the child's teardown — start the child clean
            os.register_at_fork(after_in_child=_after_fork_in_child)
        except (AttributeError, ValueError):  # pragma: no cover
            pass


def _after_fork_in_child() -> None:
    """Fork-child reset. The inherited ``_state.lock`` may be HELD by a
    parent thread that does not exist here — acquiring it (as a plain
    ``reset()`` would) could wedge the child inside ``os.fork``. The
    child is single-threaded at this instant, so swap in a fresh lock
    and clear lock-free."""
    _state.lock = _REAL_RLOCK()
    _state.edges.clear()
    _state.findings.clear()
    _state.threads.clear()
    _state.waiting.clear()
    _state.owners.clear()
    _state.reported_deadlocks.clear()


def deactivate() -> None:
    """Restore the real factories (wrapped objects keep working: they
    hold their real primitive). The watchdog thread exits on its next
    poll."""
    global _ACTIVE
    if not _ACTIVE:
        return
    _ACTIVE = False
    _state.epoch += 1   # retires the current watchdog immediately-ish
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    threading.Thread = _REAL_THREAD


def maybe_activate() -> bool:
    """Activate iff ``PDTT_SANITIZE=1`` — the one-liner for conftest
    and tool entry points."""
    if os.environ.get("PDTT_SANITIZE") == "1":
        activate()
        return True
    return False


def active() -> bool:
    return _ACTIVE


def _atexit_hook() -> None:
    check_teardown()
    path = os.environ.get("PDTT_SANITIZE_GRAPH")
    if path:
        try:
            dump_graph(path)
        except OSError:
            pass


def check_teardown() -> list[Finding]:
    """Flag non-daemon sanitized threads that were started but never
    joined. Returns the new findings."""
    new: list[Finding] = []
    with _state.lock:
        threads = list(_state.threads)
    for t in threads:
        if t.daemon or t.san_joined or not t.ident:
            continue  # never started / daemon / joined: fine
        if t is threading.current_thread():
            continue
        state = "still alive" if t.is_alive() else "finished"
        _record("unjoined_thread",
                f"non-daemon thread {t.name!r} (created at "
                f"{t.san_site}) was started but never joined — "
                f"{state} at teardown",
                thread=t.name, site=t.san_site, alive=t.is_alive())
        t.san_joined = True   # one report per thread
        with _state.lock:
            try:                # reported: drop from the registry too
                _state.threads.remove(t)
            except ValueError:
                pass
        new.append(_state.findings[-1])
    return new


# --------------------------------------------------------------- readout
def findings(kind: str | None = None) -> list[Finding]:
    with _state.lock:
        fs = list(_state.findings)
    return fs if kind is None else [f for f in fs if f.kind == kind]


def findings_summary() -> dict:
    out: dict[str, int] = {}
    for f in findings():
        out[f.kind] = out.get(f.kind, 0) + 1
    return out


def edges() -> dict:
    with _state.lock:
        return {k: dict(v) for k, v in _state.edges.items()}


def dump_graph(path: str) -> str:
    """Write the observed runtime lock-order graph as JSON — the
    ``--compare-runtime`` input."""
    with _state.lock:
        recs = [{"from": a, "to": b, "count": e["count"],
                 "thread": e["thread"], "stack": e["stack"]}
                for (a, b), e in sorted(_state.edges.items())]
        fcount = len(_state.findings)
    data = {"format": "pdtt-syncdbg-graph-v1", "edges": recs,
            "findings": fcount}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    return path


def reset() -> None:
    """Tests: drop edges/findings/thread registry (wrappers stay)."""
    with _state.lock:
        _state.edges.clear()
        _state.findings.clear()
        _state.threads.clear()
        _state.waiting.clear()
        _state.owners.clear()
        _state.reported_deadlocks.clear()
