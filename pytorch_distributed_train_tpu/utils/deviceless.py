"""Deviceless-compile environment hygiene.

When a parent python held a LIVE axon lease, its sitecustomize exports
the device identity (TPU_WORKER_HOSTNAMES / TPU_TOPOLOGY=1x1 /
TPU_ACCELERATOR_TYPE / ...) into os.environ and children inherit it;
libtpu then rejects a deviceless ``get_topology_desc`` for a DIFFERENT
topology (e.g. v5e:2x2x1) as conflicting. Tools that compile against a
TPU topology without a device (tools/aot_ab.py, tools/memfit_7b.py,
tools/mosaic_aot_battery.py) — and the test that gates them — must
drop the inherited identity BEFORE any libtpu init, from one shared
list so a newly leaked variable cannot silently diverge between them.
"""

from __future__ import annotations

import os

AXON_IDENTITY_VARS = (
    "TPU_WORKER_HOSTNAMES",
    "TPU_WORKER_ID",
    "TPU_TOPOLOGY",
    "TPU_ACCELERATOR_TYPE",
    "AXON_POOL_SVC_OVERRIDE",
)


def scrub_axon_identity(env: dict | None = None) -> dict:
    """Remove a live-lease parent's exported device identity.

    Mutates ``os.environ`` by default; pass an env dict (e.g. a
    subprocess env about to be handed to ``subprocess.run``) to scrub
    that instead. Returns the scrubbed mapping."""
    target = os.environ if env is None else env
    for var in AXON_IDENTITY_VARS:
        target.pop(var, None)
    return target
