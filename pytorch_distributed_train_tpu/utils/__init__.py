"""Utilities: metrics/logging, profiling hooks, failure detection, debug
checks (SURVEY §5.1-5.5)."""
