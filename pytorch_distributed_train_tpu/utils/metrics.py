"""Metrics: rank-0 console + JSONL step log + optional TensorBoard
(SURVEY §5.5; reference: torch:utils/tensorboard/writer.py:173 +
rank-0 console logging).

North-star instrumentation from day one: images|tokens/sec/chip and
step-time p50/p99 (BASELINE.json:2) — collected with a rolling window so the
numbers exclude compile time after the first step.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

import jax
import numpy as np


class Meter:
    """Rolling step-time / throughput meter (window excludes compile steps)."""

    def __init__(self, window: int = 200):
        self.times: deque[float] = deque(maxlen=window)
        self._last: float | None = None
        # Cumulative in-loop stepping seconds (never windowed, never
        # reset): gaps excluded by reset_clock (eval passes, epoch
        # boundaries) don't count — the honest denominator for rates that
        # must not be diluted by off-loop work (input_stall_pct).
        self.total_s = 0.0

    def tick(self) -> float | None:
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            dt = now - self._last
            self.times.append(dt)
            self.total_s += dt
        self._last = now
        return dt

    def reset_clock(self) -> None:
        self._last = None

    def percentiles(self) -> dict[str, float]:
        if not self.times:
            return {}
        arr = np.asarray(self.times)
        return {
            "step_time_ms_p50": float(np.percentile(arr, 50) * 1e3),
            "step_time_ms_p99": float(np.percentile(arr, 99) * 1e3),
        }

    def throughput(self, items_per_step: int) -> float | None:
        if not self.times:
            return None
        p50 = float(np.percentile(np.asarray(self.times), 50))
        return items_per_step / p50 if p50 > 0 else None


class MetricLogger:
    """Process-0 writer: console + JSONL (+ TensorBoard when enabled)."""

    def __init__(self, jsonl_path: str = "", tensorboard_dir: str = "",
                 is_main: bool | None = None):
        self.is_main = jax.process_index() == 0 if is_main is None else is_main
        self._jsonl = None
        self._tb = None
        if not self.is_main:
            return
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._jsonl = open(jsonl_path, "a", buffering=1)
        if tensorboard_dir:
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception:
                self._tb = None

    def log(self, step: int, metrics: dict, prefix: str = "train") -> None:
        record = {"step": step, "ts": time.time()}
        for k, v in metrics.items():
            if hasattr(v, "item"):
                v = float(np.asarray(v))
            record[k] = v
        # EVERY process mirrors its record into the scrape registry
        # (obs/registry.py) — a straggling non-zero host's sidecar must
        # show that host's own numbers; JSONL/TB/console stay rank-0.
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        get_registry().set_from_mapping(record, prefix=prefix)
        if not self.is_main:
            return
        if self._jsonl:
            self._jsonl.write(json.dumps({"tag": prefix, **record}) + "\n")
        if self._tb:
            for k, v in record.items():
                if isinstance(v, (int, float)) and k not in ("step", "ts"):
                    self._tb.add_scalar(f"{prefix}/{k}", v, step)
        shown = {
            k: (f"{v:.4f}" if isinstance(v, float) else v)
            for k, v in record.items()
            if k != "ts"
        }
        print(f"[{prefix}] " + " ".join(f"{k}={v}" for k, v in shown.items()), flush=True)

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
        if self._tb:
            self._tb.close()
