"""JAX API compatibility shims.

The codebase targets the current public API; the deployment containers
sometimes pin an older jax. Each shim resolves the modern spelling when
present and falls back to the legacy one, so the same source runs on
both — the alternative (pinning the old spelling) rots the moment the
container catches up.

``shard_map``: public ``jax.shard_map`` (with ``check_vma`` /
``axis_names``) vs legacy ``jax.experimental.shard_map.shard_map``
(``check_rep`` / complementary ``auto``). Semantics map 1:1:
``check_vma`` and ``check_rep`` are the same per-shard replication
check under its two names, and legacy ``auto`` is the complement of
``axis_names`` over the mesh axes (modern: which axes ARE manual;
legacy: which axes are NOT).

``pytree_restore_args``: modern orbax spells partial restore as
``PyTreeRestore(..., partial_restore=True)``; older orbax (this
container's 0.7.0) rejects the kwarg but expresses the same contract
with ``transforms={}`` — with ``transforms_default_to_original=True``
(the default) an empty transforms dict restores exactly the template's
leaves from their original saved values and never materializes subtrees
the template does not name.
"""

from __future__ import annotations

import inspect

import jax


def shard_map(f, *, mesh, in_specs, out_specs,
              check_vma: bool | None = None, axis_names=None):
    if hasattr(jax, "shard_map"):
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _legacy

    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    # ``axis_names`` is intentionally NOT mapped to legacy ``auto``:
    # partial-auto regions on old jax lower axis_index to a PartitionId
    # instruction old XLA's SPMD partitioner rejects ("meaning is
    # ambiguous"). Full-manual is numerically identical — axes the
    # caller wanted auto just see replicated data (in_specs that do not
    # name them), costing redundant compute on those axes only under
    # legacy jax.
    return _legacy(f, mesh, in_specs, out_specs, **kw)


def pytree_metadata_tree(ocp, item_dir: str) -> dict:
    """A saved pytree item's metadata TREE (leaves expose .shape/.dtype).
    Modern orbax returns a metadata object exposing
    ``.item_metadata.tree``; legacy orbax (0.7.x) returns the tree
    itself as a plain dict. Raises whatever the underlying reader
    raises — the caller decides whether unreadable metadata is an error
    or a "trust the layout" fallback."""
    meta = ocp.PyTreeCheckpointer().metadata(item_dir)
    if isinstance(meta, dict):
        return meta
    return dict(meta.item_metadata.tree)


def pytree_metadata_keys(ocp, item_dir: str) -> set[str]:
    """Top-level keys of a saved pytree item, either orbax spelling."""
    return set(pytree_metadata_tree(ocp, item_dir).keys())


def pytree_restore_args(ocp, item, restore_args):
    """``ocp.args.PyTreeRestore`` for a PARTIAL restore, spelled for
    whichever orbax is installed (see module docstring). ``item`` names
    only the subtrees to restore; everything else in the checkpoint is
    never deserialized on either spelling."""
    params = inspect.signature(ocp.args.PyTreeRestore.__init__).parameters
    if "partial_restore" in params:
        return ocp.args.PyTreeRestore(item=item, restore_args=restore_args,
                                      partial_restore=True)
    return ocp.args.PyTreeRestore(item=item, restore_args=restore_args,
                                  transforms={})
