"""JAX API compatibility shims.

The codebase targets the current public API; the deployment containers
sometimes pin an older jax. Each shim resolves the modern spelling when
present and falls back to the legacy one, so the same source runs on
both — the alternative (pinning the old spelling) rots the moment the
container catches up.

``shard_map``: public ``jax.shard_map`` (with ``check_vma`` /
``axis_names``) vs legacy ``jax.experimental.shard_map.shard_map``
(``check_rep`` / complementary ``auto``). Semantics map 1:1:
``check_vma`` and ``check_rep`` are the same per-shard replication
check under its two names, and legacy ``auto`` is the complement of
``axis_names`` over the mesh axes (modern: which axes ARE manual;
legacy: which axes are NOT).
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs,
              check_vma: bool | None = None, axis_names=None):
    if hasattr(jax, "shard_map"):
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _legacy

    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    # ``axis_names`` is intentionally NOT mapped to legacy ``auto``:
    # partial-auto regions on old jax lower axis_index to a PartitionId
    # instruction old XLA's SPMD partitioner rejects ("meaning is
    # ambiguous"). Full-manual is numerically identical — axes the
    # caller wanted auto just see replicated data (in_specs that do not
    # name them), costing redundant compute on those axes only under
    # legacy jax.
    return _legacy(f, mesh, in_specs, out_specs, **kw)
