"""Analytic model-FLOPs accounting and MFU (VERDICT r3 ask #2).

The reference genre measures throughput in items/sec; the question "is
this actually fast?" needs MFU — achieved FLOP/s over the chip's peak.
Nothing here traces or compiles: every number is a closed-form walk of
the architecture the configs describe (conv/matmul exact, attention
seq-aware), so the accounting is auditable and runs anywhere (including
on hosts with no device at all).

Conventions (stated once, used everywhere):

- **FLOPs = 2 x MACs** (one multiply + one add), the MLPerf / PaLM-MFU
  convention. Beware: vision-literature "GFLOPs" tables usually count
  MACs — torchvision's "4.09 GFLOPs" ResNet-50 is 4.09 GMACs = 8.2
  GFLOPs under this convention.
- **Model FLOPs, not executed FLOPs**: rematerialisation recompute,
  s2d-stem padding-tap overhead, and fused-head chunking do not change
  the number — MFU measures useful work per second, which is why a remat
  config can never "win" MFU by recomputing more.
- **Training step = 3 x forward** (backward = 2x forward, the standard
  two-matmul cotangent accounting). Elementwise/norm/pool FLOPs are
  omitted (sub-1% next to the matmuls, and not MXU work anyway).
- **Attention is counted un-masked** (full S^2), matching the PaLM MFU
  appendix; a causal model that skips half the score tile gets the
  benefit as higher measured MFU, not a smaller denominator.

Peak table: bf16 systolic-array peak per chip, from the public TPU spec
sheets, keyed by PJRT ``device_kind`` substrings.
"""

from __future__ import annotations

import math
from typing import Any

# bf16 peak TFLOP/s per chip by device_kind (PJRT strings observed in the
# wild: "TPU v5 lite", "TPU v5p", "TPU v4", "TPU v6 lite", "TPU v3").
# Ordered: first substring match wins, so "v5 lite" must precede "v5".
_PEAK_TFLOPS_BF16: tuple[tuple[str, float], ...] = (
    ("v6 lite", 918.0),   # Trillium / v6e
    ("v6e", 918.0),
    ("v5 lite", 197.0),   # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)

# HBM bandwidth GB/s per chip (public spec sheets), same matching rules.
# Decode is bandwidth-bound, so MBU — bytes actually moved per second
# over this peak — is its utilization measure, as MFU is training's.
_HBM_GBPS: tuple[tuple[str, float], ...] = (
    ("v6 lite", 1638.0),
    ("v6e", 1638.0),
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v5p", 2765.0),
    ("v5", 2765.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def device_hbm_bandwidth(device: Any = None) -> float | None:
    """HBM bytes/sec of ``device`` (default jax.devices()[0]); None off-TPU."""
    if device is None:
        import jax

        device = jax.devices()[0]
    if getattr(device, "platform", "") != "tpu":
        return None
    kind = getattr(device, "device_kind", "").lower()
    for sub, gbps in _HBM_GBPS:
        if sub in kind:
            return gbps * 1e9
    return None


def device_peak_flops(device: Any = None) -> float | None:
    """bf16 peak FLOP/s of ``device`` (default: jax.devices()[0]), or
    None when the platform has no meaningful MXU peak (CPU backend —
    reporting an "MFU" against a host core would be noise)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    if getattr(device, "platform", "") != "tpu":
        return None
    kind = getattr(device, "device_kind", "").lower()
    for sub, tflops in _PEAK_TFLOPS_BF16:
        if sub in kind:
            return tflops * 1e12
    return None


# ---------------------------------------------------------------------------
# Vision
# ---------------------------------------------------------------------------


def _conv_out(n: int, k: int, s: int, pad: int) -> int:
    return (n + 2 * pad - k) // s + 1


def resnet_fwd_flops(cfg) -> float:
    """Forward FLOPs/image for models/resnet.py's architecture, walking
    the exact stage/block/stride schedule (stage_sizes from the name).
    The s2d stem counts as the canonical 7x7 conv it computes (model
    FLOPs; the zero-padded taps are execution overhead, not work)."""
    deep = cfg.name == "resnet50"
    stage_sizes = (3, 4, 6, 3) if deep else (2, 2, 2, 2)
    img = cfg.image_size
    cifar_stem = (not deep) and img <= 64
    f0 = 64
    flops = 0.0

    if cifar_stem:
        h = _conv_out(img, 3, 1, 1)
        flops += 2.0 * h * h * f0 * 3 * 3 * 3
        cin = f0
    else:
        h = _conv_out(img, 7, 2, 3)
        flops += 2.0 * h * h * f0 * 7 * 7 * 3
        h = _conv_out(h, 3, 2, 1)  # maxpool
        cin = f0

    for i, blocks in enumerate(stage_sizes):
        f = f0 * 2 ** i
        for j in range(blocks):
            s = 2 if i > 0 and j == 0 else 1
            if deep:
                # 1x1 cin->f, 3x3/s f->f, 1x1 f->4f (+1x1/s proj cin->4f)
                flops += 2.0 * h * h * cin * f
                ho = _conv_out(h, 3, s, 1)
                flops += 2.0 * ho * ho * f * 3 * 3 * f
                flops += 2.0 * ho * ho * f * 4 * f
                if s != 1 or cin != 4 * f:
                    flops += 2.0 * ho * ho * cin * 4 * f
                cin, h = 4 * f, ho
            else:
                ho = _conv_out(h, 3, s, 1)
                flops += 2.0 * ho * ho * cin * 3 * 3 * f
                flops += 2.0 * ho * ho * f * 3 * 3 * f
                if s != 1 or cin != f:
                    flops += 2.0 * ho * ho * cin * f
                cin, h = f, ho

    flops += 2.0 * cin * cfg.num_classes  # fc after global pool
    return flops


def vit_fwd_flops(cfg) -> float:
    """Forward FLOPs/image for models/vit.py (cls token, learned pos)."""
    d, m = cfg.hidden_size, cfg.mlp_dim
    grid = cfg.image_size // cfg.patch_size
    s = grid * grid + 1  # + cls token
    # patch embedding: one matmul per patch, (patch^2 * 3) -> d
    flops = 2.0 * grid * grid * (cfg.patch_size ** 2 * 3) * d
    per_layer = (
        8.0 * s * d * d          # q,k,v,o projections
        + 4.0 * s * s * d        # QK^T and AV
        + 4.0 * s * d * m        # mlp in + out
    )
    flops += cfg.num_layers * per_layer
    flops += 2.0 * d * cfg.num_classes  # head on the cls token
    return flops


# ---------------------------------------------------------------------------
# Transformers (per token, seq-aware)
# ---------------------------------------------------------------------------


def _attn_proj_flops(cfg) -> float:
    """Per-token q/k/v/o projection FLOPs, GQA-aware."""
    d, h = cfg.hidden_size, cfg.num_heads
    hkv = cfg.num_kv_heads or h
    dh = d // h
    return 2.0 * d * d * 2 + 2.0 * d * (dh * hkv) * 2  # q+o, k+v


def llama_fwd_flops_per_token(cfg, seq: int | None = None) -> float:
    """models/llama.py: RMSNorm blocks, GQA, SwiGLU, untied head."""
    s = seq or cfg.max_seq_len
    d, m = cfg.hidden_size, cfg.mlp_dim
    per_layer = (
        _attn_proj_flops(cfg)
        + 4.0 * s * d       # QK^T + AV (un-masked convention)
        + 6.0 * d * m       # SwiGLU: gate + up + down
    )
    return cfg.num_layers * per_layer + 2.0 * d * cfg.vocab_size


def gpt2_fwd_flops_per_token(cfg, seq: int | None = None) -> float:
    """models/gpt2.py: MHA, 2-matmul GELU MLP, tied head (same FLOPs)."""
    s = seq or cfg.max_seq_len
    d, m = cfg.hidden_size, cfg.mlp_dim
    per_layer = 8.0 * d * d + 4.0 * s * d + 4.0 * d * m
    return cfg.num_layers * per_layer + 2.0 * d * cfg.vocab_size


def bert_fwd_flops_per_token(cfg, seq: int | None = None) -> float:
    """models/bert.py: post-LN MHA blocks + MLM head (dense D->D, GELU,
    LN, tied-embedding decode) computed at every position."""
    s = seq or cfg.max_seq_len
    d, m = cfg.hidden_size, cfg.mlp_dim
    per_layer = 8.0 * d * d + 4.0 * s * d + 4.0 * d * m
    head = 2.0 * d * d + 2.0 * d * cfg.vocab_size
    return cfg.num_layers * per_layer + head


def t5_fwd_flops_per_token(cfg, src: int | None = None,
                           tgt: int | None = None) -> float:
    """models/t5.py enc-dec, amortised PER TOKEN over (src + tgt) tokens
    — matching the bench/trainer convention that counts encoder source +
    decoder target tokens as the throughput denominator. DenseReluDense
    (2 matmuls), decoder adds cross-attention over the src length."""
    s_src = src or cfg.max_seq_len
    s_tgt = tgt or max(s_src // 4, 1)
    d, m = cfg.hidden_size, cfg.mlp_dim
    dec_layers = cfg.decoder_layers or cfg.num_layers
    enc_layer = 8.0 * d * d + 4.0 * s_src * d + 4.0 * d * m
    dec_layer = (
        8.0 * d * d + 4.0 * s_tgt * d       # self-attention
        + 8.0 * d * d + 4.0 * s_src * d     # cross-attention (q from tgt)
        + 4.0 * d * m
    )
    enc_total = cfg.num_layers * enc_layer * s_src
    dec_total = dec_layers * dec_layer * s_tgt
    head_total = 2.0 * d * cfg.vocab_size * s_tgt
    return (enc_total + dec_total + head_total) / (s_src + s_tgt)


# ---------------------------------------------------------------------------
# Dispatch + MFU
# ---------------------------------------------------------------------------

# model name -> (fn(cfg, seq) -> fwd FLOPs per ITEM, item noun). The item
# matches the throughput unit bench.py / the trainer report: images for
# vision, tokens for LMs (t5: source+target tokens).
_FWD = {
    "resnet18": (lambda cfg, seq: resnet_fwd_flops(cfg), "image"),
    "resnet50": (lambda cfg, seq: resnet_fwd_flops(cfg), "image"),
    "vit_b16": (lambda cfg, seq: vit_fwd_flops(cfg), "image"),
    "llama": (llama_fwd_flops_per_token, "token"),
    "llama_pp": (llama_fwd_flops_per_token, "token"),
    "gpt2": (gpt2_fwd_flops_per_token, "token"),
    "bert_base": (bert_fwd_flops_per_token, "token"),
    "t5": (lambda cfg, seq: t5_fwd_flops_per_token(cfg, seq), "token"),
}


def fwd_flops_per_item(model_cfg, seq: int | None = None) -> float | None:
    """Forward FLOPs per throughput item (image or token), or None for
    models without an accounting entry."""
    entry = _FWD.get(model_cfg.name)
    if entry is None:
        return None
    return entry[0](model_cfg, seq)


def train_flops_per_item(model_cfg, seq: int | None = None) -> float | None:
    """fwd + bwd FLOPs per item for one training step (3 x forward)."""
    fwd = fwd_flops_per_item(model_cfg, seq)
    return None if fwd is None else 3.0 * fwd


def aot_fwd_flops_per_item(model_cfg, precision_cfg=None, *,
                           seq: int | None = None,
                           batch: int = 1) -> float | None:
    """XLA's own forward FLOP count per item, from jax AOT
    ``lower(...).cost_analysis()`` — the independent cross-check that
    keeps the hand-rolled formulas above from silently drifting when a
    model changes (tests compare this against ``fwd_flops_per_item``
    within tolerance). HLO-level only: lowering, no backend compile, so
    it runs in seconds on the CPU test backend. Returns None when the
    model has no throughput-item convention here (unlisted name) or the
    lowering exposes no flops estimate.

    The item denominator matches ``fwd_flops_per_item``: images for
    vision models, tokens for LMs (batch * seq tokens per forward).
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu.config import PrecisionConfig
    from pytorch_distributed_train_tpu.models.registry import build_model

    entry = _FWD.get(model_cfg.name)
    if entry is None or model_cfg.name == "t5":
        # t5's per-token amortisation spans two sequences (src + tgt);
        # the single-input lowering here doesn't model it.
        return None
    if precision_cfg is None:
        # fp32 lowering: cost_analysis counts the same dot/conv flops
        # regardless, and fp32 avoids backend-specific bf16 expansions.
        precision_cfg = PrecisionConfig(compute_dtype="float32")
    model = build_model(model_cfg, precision_cfg)
    noun = entry[1]
    if noun == "image":
        x = jnp.zeros((batch, model_cfg.image_size, model_cfg.image_size,
                       3), jnp.float32)
        items = batch
    else:
        s = seq or model_cfg.max_seq_len
        x = jnp.zeros((batch, s), jnp.int32)
        items = batch * s

    def fwd(params, inputs):
        return model.apply(params, inputs, train=False)

    params = jax.eval_shape(
        lambda r: model.init({"params": r}, x, train=False),
        jax.random.PRNGKey(0))
    x_shape = jax.ShapeDtypeStruct(x.shape, x.dtype)
    try:
        cost = jax.jit(fwd).lower(params, x_shape).cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):  # some backends wrap per-device
        cost = cost[0] if cost else {}
    flops = (cost or {}).get("flops")
    if not flops or flops <= 0:
        return None
    return float(flops) / items


def llama_param_count(cfg) -> float:
    """Exact parameter count for models/llama.py's architecture (GQA,
    SwiGLU, untied head; norms counted — they read like everything else)."""
    d, m, h = cfg.hidden_size, cfg.mlp_dim, cfg.num_heads
    hkv = cfg.num_kv_heads or h
    dh = d // h
    per_layer = (
        d * d + d * d                 # q_proj + o_proj
        + 2 * d * (hkv * dh)          # k_proj + v_proj
        + 3 * d * m                   # SwiGLU gate/up/down
        + 2 * d                       # two RMSNorm scales
    )
    return (cfg.num_layers * per_layer
            + 2 * cfg.vocab_size * d  # embedding + untied head
            + d)                      # final norm


def decode_bytes_per_token(cfg, *, batch: int, avg_position: float,
                           weight_bytes_per_param: float = 2.0,
                           kv_bytes_per_elt: float = 2.0) -> float:
    """HBM bytes a llama-family model must MOVE per generated token: the
    full weight read amortized over the batch (every row shares one pass)
    plus the row's own K/V cache read at ``avg_position`` fill. This is
    the decode-side roofline denominator — tokens/sec x this, over the
    chip's HBM bandwidth, is MBU. Weight/kv byte sizes parameterize the
    quantization levers (int8 = 1, int4 = 0.5, fp8 kv = 1)."""
    d, h = cfg.hidden_size, cfg.num_heads
    hkv = cfg.num_kv_heads or h
    dh = d // h
    weights = llama_param_count(cfg) * weight_bytes_per_param / max(batch, 1)
    kv_read = 2.0 * cfg.num_layers * hkv * dh * avg_position \
        * kv_bytes_per_elt
    return weights + kv_read


def mbu_pct(tokens_per_sec_per_chip: float, bytes_per_token: float | None,
            bandwidth: float | None) -> float | None:
    """Model-bandwidth utilization %: moved bytes/sec over HBM peak."""
    if not bytes_per_token or not bandwidth:
        return None
    if not math.isfinite(tokens_per_sec_per_chip):
        return None
    return 100.0 * tokens_per_sec_per_chip * bytes_per_token / bandwidth


def mfu_pct(items_per_sec_per_chip: float, flops_per_item: float | None,
            peak_flops: float | None) -> float | None:
    """Achieved / peak FLOP/s as a percentage; None when either side of
    the ratio is unknown (no accounting entry, or a CPU backend)."""
    if not flops_per_item or not peak_flops:
        return None
    if not math.isfinite(items_per_sec_per_chip):
        return None
    return 100.0 * items_per_sec_per_chip * flops_per_item / peak_flops
