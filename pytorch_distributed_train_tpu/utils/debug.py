"""Debug-mode divergence checks (SURVEY §5.2).

The reference's TORCH_DISTRIBUTED_DEBUG=DETAIL wraps process groups to
cross-check collective op+shape across ranks before each call
(torch:distributed/distributed_c10d.py:2282-2308). Under SPMD that race
class is unauthorable — one program, compiler-placed collectives. What CAN
still diverge is the host side: per-host input pipelines feeding
different-shaped or differently-ordered batches. These helpers catch that.
"""

from __future__ import annotations

import hashlib

import jax
import numpy as np


def batch_signature(batch: dict) -> str:
    """Stable hash of structure+shapes+dtypes (cheap) of a HOST-LOCAL batch.

    Must be called on numpy batches before global-array assembly (the
    pipeline wires this via sync_check_every) — after assembly every host
    sees identical global shapes by construction. Content is intentionally
    not hashed: host shards legitimately differ."""
    h = hashlib.sha256()
    for k in sorted(batch):
        v = batch[k]
        h.update(k.encode())
        h.update(str(np.asarray(v).shape).encode())
        h.update(str(np.asarray(v).dtype).encode())
    return h.hexdigest()[:16]


def check_input_sync(batch: dict) -> None:
    """Assert all hosts assembled structurally identical batches this step.

    Cross-host gather of the signature; raises on divergence. Call at debug
    cadence only (obs.check_input_sync_every) — it is a blocking collective
    off the step path.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    sig = batch_signature(batch)
    sig_bytes = np.frombuffer(sig.encode(), dtype=np.uint8)
    all_sigs = multihost_utils.process_allgather(sig_bytes)
    first = bytes(np.asarray(all_sigs[0]).tobytes())
    for i in range(1, all_sigs.shape[0]):
        if bytes(np.asarray(all_sigs[i]).tobytes()) != first:
            raise RuntimeError(
                f"input pipeline divergence: host 0 sig {first!r} != host {i}"
            )


def enable_nan_debugging() -> None:
    """jax.debug_nans — the analogue of torch's anomaly detection /
    NanCheck.hpp in the NCCL path."""
    jax.config.update("jax_debug_nans", True)
