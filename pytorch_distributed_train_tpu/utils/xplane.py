"""XPlane trace reader: turn jax.profiler dumps into step-time reports.

The reference stack reads Kineto traces in TensorBoard or chrome://tracing
(torch:profiler/profiler.py:773 `profile`, SURVEY §5.1). On TPU the profiler
emits XPlane protobufs; the TensorBoard profile plugin renders them, but an
operator debugging throughput wants the top-ops table WITHOUT a TensorBoard
server — this module aggregates a dump directly:

    python -m pytorch_distributed_train_tpu.utils.xplane /tmp/trace --top 20

Works on the `*.xplane.pb` files produced by `jax.profiler.trace` (the
trainer's obs.profile_* window writes them). Op names are classified into
MXU/HBM-meaningful buckets (fusion, convolution, matmul, collective, copy,
infeed/outfeed) so the report answers "where did the step go" at a glance.
"""

from __future__ import annotations

import collections
import glob
import os
from typing import Any

_CLASS_PATTERNS = (
    ("collective", ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")),
    ("convolution", ("convolution", "conv")),
    ("matmul", ("dot", "einsum")),
    ("copy", ("copy",)),
    ("infeed/outfeed", ("infeed", "outfeed", "send", "recv")),
    ("fusion", ("fusion",)),
)


def classify_op(name: str) -> str:
    """HLO-ish op name → report bucket."""
    n = name.lower().lstrip("%")
    for cls, pats in _CLASS_PATTERNS:
        if any(p in n for p in pats):
            return cls
    return "other"


# Perf-attribution taxonomy (obs/perf.py; docs/performance.md): a CLOSED
# roofline-meaningful vocabulary, distinct from the human report buckets
# above. Ordered — first match wins — so attention fusions (named
# "...attn..."/"flash..." by the pallas kernels and xla fusion naming)
# claim their ops before the generic matmul/elementwise patterns do, and
# data movement (copy/infeed) is never mistaken for compute. Plain
# "fusion.N" names are predominantly XLA loop fusions → elementwise; a
# fusion whose name carries dot/conv hints lands in the right compute
# class via the earlier patterns.
PERF_OP_CLASSES = ("matmul", "conv", "attention", "elementwise",
                   "collective", "infeed")

_PERF_CLASS_PATTERNS = (
    ("collective", ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all", "psum",
                    "ppermute")),
    ("infeed", ("infeed", "outfeed", "send", "recv", "copy",
                "transfer", "host")),
    ("attention", ("attention", "attn", "flash", "mha", "sdpa")),
    ("conv", ("convolution", "conv")),
    ("matmul", ("dot", "einsum", "gemm", "matmul")),
    ("elementwise", ("fusion", "add", "subtract", "multiply", "divide",
                     "exp", "tanh", "rsqrt", "sqrt", "log", "power",
                     "reduce", "broadcast", "select", "compare",
                     "convert", "maximum", "minimum", "scatter",
                     "gather", "slice", "pad", "transpose", "reshape",
                     "iota", "concatenate", "clamp", "softmax", "norm",
                     "bitcast", "and", "or", "not", "floor", "sort")),
)


def classify_op_class(name: str) -> str:
    """HLO-ish op name → perf op class (matmul/conv/attention/
    elementwise/collective/infeed), "other" when nothing matches."""
    n = name.lower().lstrip("%")
    for cls, pats in _PERF_CLASS_PATTERNS:
        if any(p in n for p in pats):
            return cls
    return "other"


def opclass_split(ops) -> dict[str, float]:
    """``[(name, ms, count), ...]`` (summarize_xspace's per-plane op
    list) → milliseconds per perf op class, zero classes dropped."""
    out = collections.Counter()
    for name, ms, _count in ops:
        out[classify_op_class(name)] += ms
    return {c: float(ms) for c, ms in out.most_common() if ms > 0}


def _import_xplane_pb2():
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore
        return xplane_pb2
    except Exception as e:  # pragma: no cover - env-specific
        raise ImportError(
            "reading xplane dumps needs the tsl xplane proto "
            "(tensorflow.tsl.profiler.protobuf.xplane_pb2); not available "
            f"in this environment: {e}"
        ) from None


def load_xspace(path: str):
    """Parse one .xplane.pb file."""
    xplane_pb2 = _import_xplane_pb2()
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def find_xplane_files(logdir: str) -> list[str]:
    """Newest-first xplane dumps under a jax.profiler logdir."""
    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    return sorted(paths, key=os.path.getmtime, reverse=True)


def summarize_xspace(xs, device_only: bool = True) -> list[dict[str, Any]]:
    """Per-plane aggregation: op totals, counts, and class buckets.

    Returns one dict per plane: {plane, total_ms, ops: [(name, ms, count)...]
    (descending), by_class: {cls: ms}}. ``device_only`` keeps planes whose
    name mentions TPU/GPU (the host CPU plane is python-profiling noise for
    a step-time report).
    """
    out = []
    for plane in xs.planes:
        if device_only and not any(
            tag in plane.name for tag in ("TPU", "GPU", "/device:")
        ):
            continue
        ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
        total_ps = collections.Counter()
        count = collections.Counter()
        for line in plane.lines:
            for ev in line.events:
                name = ev_meta.get(ev.metadata_id, f"id{ev.metadata_id}")
                total_ps[name] += ev.duration_ps
                count[name] += 1
        by_class = collections.Counter()
        for name, ps in total_ps.items():
            by_class[classify_op(name)] += ps
        out.append({
            "plane": plane.name,
            "total_ms": sum(total_ps.values()) / 1e9,
            "ops": [(n, ps / 1e9, count[n])
                    for n, ps in total_ps.most_common()],
            "by_class": {c: ps / 1e9 for c, ps in by_class.most_common()},
        })
    return out


def report(logdir: str, top: int = 15) -> str:
    """Human-readable top-ops report for the newest dump in ``logdir``."""
    files = find_xplane_files(logdir)
    if not files:
        return f"no *.xplane.pb files under {logdir}"
    lines = [f"trace: {files[0]}"]
    xs = load_xspace(files[0])
    planes = summarize_xspace(xs)
    if not planes:  # CPU-only trace (tests, local debugging): show all
        planes = summarize_xspace(xs, device_only=False)
    for plane in planes:
        lines.append(f"\n=== {plane['plane']} — {plane['total_ms']:.1f} ms "
                     "summed over trace lines ===")
        lines.append("  by class:")
        for cls, ms in plane["by_class"].items():
            pct = 100.0 * ms / max(plane["total_ms"], 1e-9)
            lines.append(f"    {ms:10.2f} ms  {pct:5.1f}%  {cls}")
        lines.append(f"  top {top} ops:")
        for name, ms, n in plane["ops"][:top]:
            lines.append(f"    {ms:10.2f} ms  n={n:<6d} {name[:100]}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("logdir", help="jax.profiler trace dir (or a .xplane.pb)")
    p.add_argument("--top", type=int, default=15)
    args = p.parse_args(argv)
    logdir = args.logdir
    if logdir.endswith(".xplane.pb"):
        logdir = os.path.dirname(logdir)
    print(report(logdir, top=args.top))


if __name__ == "__main__":
    main()
