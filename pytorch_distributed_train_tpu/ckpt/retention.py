"""Retention policy for the hot checkpoint tiers.

One pure planner shared by the RAM and disk tiers so they age
coherently (a step evicted from RAM but kept on disk is fine; a step
the policy *pins* is pinned in both). The persistent (Orbax) tier keeps
its own ``max_to_keep`` — this module governs only the hot copies.

Keep rules, in priority order:

- **pin** — steps the caller marks unevictable. The manager always pins
  the newest integrity-verified persistent step (``latest_good_step``)
  and the newest sealed hot step: GC must never delete the state every
  recovery path would reach for next.
- **keep-every-K** — ``step % keep_every == 0`` survives (sparse
  long-horizon rewind points). 0 disables.
- **keep-last-N** — the newest ``keep_last`` steps survive.

Everything else is evicted. The planner returns the eviction list; the
manager applies it to each tier.
"""

from __future__ import annotations


def plan_evictions(steps, *, keep_last: int, keep_every: int = 0,
                   pinned=()) -> list[int]:
    """Steps to evict from a hot tier holding ``steps``.

    >>> plan_evictions([1, 2, 3, 4], keep_last=2)
    [1, 2]
    >>> plan_evictions([10, 20, 30, 40], keep_last=1, keep_every=20)
    [10, 30]
    >>> plan_evictions([1, 2, 3], keep_last=1, pinned=[1])
    [2]
    """
    steps = sorted(int(s) for s in steps)
    pins = {int(s) for s in pinned}
    keep = set(steps[-max(int(keep_last), 0):] if keep_last > 0 else [])
    if keep_every > 0:
        keep |= {s for s in steps if s % keep_every == 0}
    keep |= pins & set(steps)
    return [s for s in steps if s not in keep]
