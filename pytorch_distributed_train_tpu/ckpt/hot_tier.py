"""Hot checkpoint tiers: host RAM and per-host local disk.

The persistent (Orbax) tier is durable but slow to both write and read;
the faults the sentinel and the elastic agent actually recover from —
loss divergence, a crashed worker respawned on the SAME host — don't
need durability, they need the newest good state back *now*. Two hot
tiers provide that:

- **RamTier** — the last K sealed ``Snapshot``s, in-process. Serves a
  sentinel auto-rewind (same process, milliseconds) and is lost with
  the process, by design.
- **DiskTier** — the same snapshots spilled to a per-host local
  directory (``<ckpt dir>/hot/host_<n>`` by default). Survives a
  process kill, so a same-host elastic gang restart restores without
  re-reading persistent storage. Layout per step::

      <root>/step_<N>/meta.json   (snapshot header: CRCs, sealed flag)
      <root>/step_<N>/data.npz    (flatten-ordered leaves)

  Spills are atomic (write into ``step_<N>.tmp``, fsync-less
  ``os.replace`` rename): a kill mid-spill leaves a tmp directory the
  next process ignores and GCs, never a half-step that parses.

Both tiers are inventory + bytes only; *what a tree means* (structure,
shardings) always comes from the restorer's template — see
ckpt/snapshot.py.
"""

from __future__ import annotations

import json
import os
import shutil
import threading

import numpy as np

from pytorch_distributed_train_tpu.ckpt import snapshot as snapshot_lib


class RamTier:
    """Step → sealed-or-sealing Snapshot, bounded by retention GC (the
    manager evicts; this class only stores). Thread model: the step
    loop puts, the persister seals/spills, a rewind gets — one lock."""

    def __init__(self):
        self._snaps: dict[int, snapshot_lib.Snapshot] = {}
        self._lock = threading.Lock()

    def put(self, snap: snapshot_lib.Snapshot) -> None:
        with self._lock:
            self._snaps[snap.step] = snap

    def get(self, step: int) -> snapshot_lib.Snapshot | None:
        with self._lock:
            return self._snaps.get(int(step))

    def steps(self) -> list[int]:
        with self._lock:
            return sorted(self._snaps)

    def sealed_steps(self) -> list[int]:
        with self._lock:
            return sorted(s for s, snap in self._snaps.items() if snap.sealed)

    def evict(self, step: int) -> None:
        with self._lock:
            self._snaps.pop(int(step), None)

    def nbytes(self) -> int:
        with self._lock:
            return sum(s.nbytes() for s in self._snaps.values())


class DiskTier:
    """Per-host local spill directory for sealed snapshots."""

    def __init__(self, root: str):
        self.root = root

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step)}")

    # ---------------------------------------------------------------- write
    def spill(self, snap: snapshot_lib.Snapshot) -> str:
        """Atomically write a sealed snapshot; returns the step dir."""
        final = self._step_dir(snap.step)
        if os.path.isdir(final):
            return final
        tmp = final + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "data.npz"), "wb") as f:
            f.write(snapshot_lib.serialize_leaves(snap))
        with open(os.path.join(tmp, "meta.json"), "wb") as f:
            f.write(snapshot_lib.header_json(snap))
        os.replace(tmp, final)
        return final

    # ----------------------------------------------------------------- read
    def steps(self) -> list[int]:
        """Committed (final-named) step dirs, oldest→newest. Tmp dirs
        from a mid-spill kill are invisible here."""
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            if not name.startswith("step_") or name.endswith(".tmp"):
                continue
            try:
                out.append(int(name[len("step_"):]))
            except ValueError:
                continue
        return sorted(out)

    def sealed_steps(self) -> list[int]:
        out = []
        for s in self.steps():
            header = self.header(s)  # one read+parse per step
            if header is not None and header.get("sealed"):
                out.append(s)
        return out

    def header(self, step: int) -> dict | None:
        try:
            with open(os.path.join(self._step_dir(step), "meta.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def load(self, step: int) -> tuple[list[np.ndarray], dict] | None:
        """CRC-verified (leaves, header) for a spilled step, or None
        when the step is absent/corrupt — the caller falls back a
        tier, it never restores unverified bytes from here."""
        header = self.header(step)
        if header is None:
            return None
        try:
            with open(os.path.join(self._step_dir(step), "data.npz"),
                      "rb") as f:
                payload = f.read()
        except OSError:
            return None
        if not snapshot_lib.verify_payload(payload, header):
            return None
        return snapshot_lib.deserialize_leaves(payload), header

    # ------------------------------------------------------------------- gc
    def evict(self, step: int) -> None:
        shutil.rmtree(self._step_dir(step), ignore_errors=True)

    def gc_tmp(self) -> None:
        """Drop leftover ``.tmp`` dirs from a mid-spill kill."""
        if not os.path.isdir(self.root):
            return
        for name in os.listdir(self.root):
            if name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    def step_nbytes(self, step: int) -> int:
        sdir = self._step_dir(step)
        total = 0
        for dirpath, _, names in os.walk(sdir):
            for n in names:
                try:
                    total += os.path.getsize(os.path.join(dirpath, n))
                except OSError:
                    pass
        return total
