"""Tiered checkpoint manager: async save, hot restore, coherent GC.

``TieredCheckpointManager`` wraps the Orbax-backed ``CheckpointManager``
(checkpoint.py) with the tier stack this package provides::

    save boundary:   snapshot (device→host copy; the ONLY blocking part)
                       └─ background persister thread:
                            seal → disk spill → peer publish
                            → Orbax write + integrity manifest → GC
    restore:         RAM → local disk → peer store → Orbax
                     (each tier verified; corruption falls through)

The public surface mirrors ``CheckpointManager`` (save / maybe_save /
restore / latest_good_step / wait / close), so trainer.py, the sentinel
rewind, and the elastic resume path switch planes with a config flag
(``checkpoint.tiered``) instead of new call sites.

Metric contract (obs registry):

- ``ckpt_blocking_ms`` / ``ckpt_last_blocking_ms`` — snapshot copy time,
  the step loop's whole exposure to a save.
- ``ckpt_persist_ms`` / ``ckpt_last_persist_ms`` — background pipeline
  time for the same step (seal→…→manifest).
- ``ckpt_drain_ms`` + the ``ckpt.drain`` goodput bucket — back-pressure:
  the previous persist was still in flight when this boundary arrived
  (at most one persist runs at a time; see ckpt/persister.py).
- ``ckpt_restore_tier_total{tier=ram|disk|peer|orbax}`` — which tier
  served each restore (the sentinel-rewind acceptance gate).
- ``ckpt_hot_corrupt_total`` — hot candidates that failed verification
  and were fallen past.
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax

from pytorch_distributed_train_tpu import checkpoint as checkpoint_lib
from pytorch_distributed_train_tpu.ckpt import hot_tier, peer, retention
from pytorch_distributed_train_tpu.ckpt import snapshot as snapshot_lib
from pytorch_distributed_train_tpu.ckpt.persister import Persister
from pytorch_distributed_train_tpu.faults import registry as faults_registry
from pytorch_distributed_train_tpu.faults import retry as retry_lib
from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.obs.spans import span

# millisecond-denominated histograms (the registry default is seconds)
_MS_BUCKETS = tuple(0.5 * 2 ** i for i in range(20))  # 0.5ms .. ~262s


def _default_host_id() -> int:
    """This host's peer-plane identity. A tpurun gang of SINGLE-process
    jax runtimes (the CPU drills; one-runtime-per-host deployments) has
    jax.process_index()==0 on EVERY worker — publishing under it would
    collide all hosts onto one store slot. The launcher env rank is the
    truth whenever the launcher world is wider than the jax one."""
    from pytorch_distributed_train_tpu.elastic import elastic_world

    world, rank = elastic_world()
    if world > jax.process_count():
        return rank
    return jax.process_index()


def hot_dir_for(ckpt_cfg, host: int) -> str:
    """Per-host local spill directory: hosts must not share one (their
    shards differ and a dying host's half-spill must not shadow a
    healthy sibling's)."""
    base = getattr(ckpt_cfg, "hot_dir", "") or os.path.join(
        ckpt_cfg.dir, "hot")
    return os.path.join(base, f"host_{int(host)}")


class TieredCheckpointManager:
    def __init__(self, ckpt_cfg, config_json: str = "", *,
                 goodput=None, store=None, host_id: int | None = None,
                 peer_hosts=None, run_meta: dict | None = None):
        self.cfg = ckpt_cfg
        # The inner Orbax manager always saves SYNCHRONOUSLY: asynchrony
        # lives in our persister thread, and stacking Orbax's async
        # machinery under it would leave wait() with two queues to
        # reason about.
        self.persistent = checkpoint_lib.CheckpointManager(
            dataclasses.replace(ckpt_cfg, async_save=False), config_json,
            run_meta=run_meta)
        self.dir = self.persistent.dir
        self.goodput = goodput
        self.host = int(host_id if host_id is not None
                        else _default_host_id())
        self._peer_hosts = peer_hosts
        self._store = store
        self._store_resolved = store is not None
        self.ram = hot_tier.RamTier()
        self.disk = None
        if getattr(ckpt_cfg, "hot_disk", True):
            self.disk = hot_tier.DiskTier(hot_dir_for(ckpt_cfg, self.host))
            self.disk.gc_tmp()
        self.persister = Persister()
        self._snapshot_unsupported = False  # sticky sync-save fallback
        reg = get_registry()
        self._blocking_hist = reg.histogram(
            "ckpt_blocking_ms", buckets=_MS_BUCKETS,
            help="step-boundary blocking milliseconds per tiered save "
                 "(device->host snapshot only)")
        self._persist_hist = reg.histogram(
            "ckpt_persist_ms", buckets=_MS_BUCKETS,
            help="background persist milliseconds per tiered save "
                 "(seal + spill + publish + Orbax + manifest)")
        self._drain_hist = reg.histogram(
            "ckpt_drain_ms", buckets=_MS_BUCKETS,
            help="milliseconds a save boundary waited for the previous "
                 "persist (back-pressure)")

    # ---------------------------------------------------------------- store
    def _get_store(self):
        """The launcher store the peer tier publishes/fetches through,
        wrapped in store_plane.ResilientStore: every peer-tier store op
        gets a bounded timeout + retry, and during an outage the tier
        fails CLOSED in bounded time — restore falls through to the
        next tier (Orbax) instead of wedging a rewind behind a dead
        socket. None when the run has no launcher store."""
        if not self._store_resolved:
            self._store_resolved = True
            try:
                from pytorch_distributed_train_tpu import store_plane

                self._store = store_plane.resilient_worker_store(
                    name="ckpt-peer")
            except Exception:
                self._store = None
        return self._store

    def _hosts(self):
        """Host ids that may have published peer snapshots. After an
        elastic SHRINK the current world is smaller than the one that
        published — enumerate the job's MAXIMUM world (the agent's
        elastic/world_max store key), so a lost host's still-stored
        snapshot stays reachable from its old rank."""
        if self._peer_hosts is not None:
            return list(self._peer_hosts)
        from pytorch_distributed_train_tpu.elastic import (
            elastic_world,
            store_world_max,
        )

        fallback = max(jax.process_count(), elastic_world()[0])
        return list(range(store_world_max(self._get_store(), fallback)))

    # ----------------------------------------------------------------- save
    def _known_steps(self) -> set[int]:
        known = set(self.ram.steps())
        if self.disk is not None:
            known.update(self.disk.steps())
        try:
            known.update(int(s) for s in self.persistent.mgr.all_steps())
        except Exception:
            pass
        return known

    def save(self, state, *, epoch: int = 0, force: bool = False,
             step: int | None = None, overwrite: bool = False,
             extra_meta: dict | None = None) -> bool:
        if step is None:
            step = int(state.step)
        if step in self._known_steps() and not overwrite:
            return False  # same contract as CheckpointManager.save
        # Back-pressure: at most one persist in flight. Waiting here is
        # the honest cost of a save cadence faster than storage — it is
        # measured (ckpt_drain_ms) and re-attributed to the ckpt.drain
        # goodput bucket, never hidden in an unbounded snapshot queue.
        if self.persister.busy:
            with span("checkpoint.drain", step=step):
                try:
                    waited = self.persister.drain()
                except TimeoutError:
                    raise
                except Exception:
                    # terminal failure of the PREVIOUS persist: already
                    # printed + counted by the persister; this boundary
                    # still gets its own snapshot/persist attempt
                    waited = 0.0
            self._drain_hist.observe(waited * 1e3)
            if self.goodput is not None and waited > 0:
                self.goodput.reattribute("ckpt", "ckpt.drain", waited)
        if overwrite and step in self._known_steps():
            # Stale hot copies of the step must go AFTER the drain: an
            # in-flight persist of the OLD snapshot would otherwise
            # re-spill it mid-eviction, and the fresh spill's idempotence
            # guard would then keep the superseded bytes as the disk-
            # tier restore source. (Persistent-tier overwrite is handled
            # by CheckpointManager.save itself.)
            self.ram.evict(step)
            if self.disk is not None:
                self.disk.evict(step)
        # run_meta (world/global_batch bookkeeping) rides the snapshot
        # header too: a hot-tier restore must detect a reshard exactly
        # like an Orbax one.
        meta = {"epoch": int(epoch), **self.persistent.run_meta,
                **(extra_meta or {})}
        if self._snapshot_unsupported:
            # Sticky from the first failure: a multi-host job whose
            # arrays span hosts must not re-copy gigabytes host-side
            # and re-fail at every save boundary. The peer tier still
            # exists for it: each host publishes only the SHARDS it
            # owns (snapshot.take_shard_snapshot), and a restoring
            # survivor reassembles the global leaves from every host's
            # payload — the elastic-reshard fast path that skips the
            # Orbax round-trip.
            self._publish_shards(state, step=step, epoch=epoch, meta=meta)
            return self.persistent.save(
                state, epoch=epoch, force=force, step=step,
                overwrite=overwrite, extra_meta=extra_meta)
        t0 = time.perf_counter()
        try:
            with span("checkpoint.snapshot", step=step):
                snap = snapshot_lib.take_snapshot(
                    checkpoint_lib._savable(state), step=step, epoch=epoch,
                    meta=meta, origin=self.dir)
        except Exception as e:
            self._snapshot_unsupported = True
            # Non-fully-addressable arrays (multi-host GSPMD spanning
            # hosts): the hot plane can't copy them out — fall back to
            # the sharded synchronous Orbax path rather than guess.
            get_registry().counter(
                "ckpt_snapshot_fallback_total",
                help="tiered saves that fell back to the synchronous "
                     "Orbax path (snapshot not host-addressable)").inc()
            print(f"[ckpt] snapshot of step {step} not host-addressable "
                  f"({type(e).__name__}: {e}); saving synchronously",
                  flush=True)
            # Publish shards on THIS save too, not only the sticky
            # branch: a host lost before the next boundary must find
            # the first fallback step on the peer plane as well.
            self._publish_shards(state, step=step, epoch=epoch, meta=meta)
            return self.persistent.save(
                state, epoch=epoch, force=force, step=step,
                overwrite=overwrite, extra_meta=extra_meta)
        blocking_ms = (time.perf_counter() - t0) * 1e3
        self._blocking_hist.observe(blocking_ms)
        get_registry().gauge(
            "ckpt_last_blocking_ms",
            help="snapshot copy ms of the most recent tiered save").set(
            blocking_ms)
        events_lib.emit("ckpt", "snapshot", step=step,
                        blocking_ms=round(blocking_ms, 3))
        self.ram.put(snap)
        self.persister.submit(
            snap, lambda s: self._persist(s, force=force,
                                          overwrite=overwrite,
                                          extra_meta=extra_meta))
        return True

    def maybe_save(self, state, *, epoch: int = 0,
                   step: int | None = None) -> bool:
        if step is None:
            step = int(state.step)
        if self.cfg.save_every_steps and step % self.cfg.save_every_steps == 0:
            return self.save(state, epoch=epoch, step=step)
        return False

    # ------------------------------------------------------------- persist
    def _persist(self, snap: snapshot_lib.Snapshot, *, force: bool,
                 overwrite: bool, extra_meta: dict | None) -> None:
        """Persister-thread pipeline for one snapshot. Ordering is the
        recovery contract: by the time the (retryable, killable) Orbax
        write starts, the snapshot is already sealed and spilled — a
        kill during persist costs durability of THIS step on the
        persistent tier only; the hot tiers still restore it."""
        t0 = time.perf_counter()
        with span("checkpoint.persist", step=snap.step):
            snapshot_lib.seal(snap)
            if self.disk is not None:
                try:
                    self.disk.spill(snap)
                except OSError as e:
                    print(f"[ckpt] hot-disk spill of step {snap.step} "
                          f"failed ({e}); RAM + persistent tiers remain",
                          flush=True)
            self._maybe_publish(snap)

            def _orbax_save():
                # `ckpt.persist_io` fault point: transient persistent-
                # storage errors on the BACKGROUND path, distinct from
                # ckpt.save_io (the save call itself) so chaos schedules
                # can target the async plane specifically.
                faults_registry.maybe_fire("ckpt.persist_io",
                                           step=snap.step)
                return self.persistent.save(
                    snap.tree, epoch=snap.epoch, step=snap.step,
                    force=force, overwrite=overwrite,
                    extra_meta=extra_meta)

            retry_lib.retry_call(_orbax_save, point="ckpt.persist_io")
        persist_ms = (time.perf_counter() - t0) * 1e3
        self._persist_hist.observe(persist_ms)
        get_registry().gauge(
            "ckpt_last_persist_ms",
            help="background persist ms of the most recent tiered "
                 "save").set(persist_ms)
        events_lib.emit("ckpt", "persist", step=snap.step,
                        persist_ms=round(persist_ms, 3))
        self._gc()

    def _publish_shards(self, state, *, step, epoch, meta) -> None:
        """Best-effort per-host shard publication for states the full
        snapshot cannot copy (multi-host GSPMD). Synchronous but small:
        only this host's owned shards are serialized."""
        if not getattr(self.cfg, "peer_fetch", True):
            return
        store = self._get_store()
        if store is None:
            return
        try:
            savable = checkpoint_lib._savable(state)
            cap = getattr(self.cfg, "peer_publish_max_bytes", 64 << 20)
            if snapshot_lib.owned_shard_nbytes(savable) > cap:
                # Pre-filter on raw bytes (the npz payload is never
                # smaller), same as _maybe_publish: a 7B-scale run in
                # this branch must not pay device→host copies + encode
                # on EVERY save boundary just to discard the payload.
                return
            payload, header = snapshot_lib.take_shard_snapshot(
                savable, step=step, epoch=epoch, meta=meta,
                origin=self.dir)
            if len(payload) > cap:
                return
            peer.publish(store, self.host, header, payload)
        except Exception as e:
            print(f"[ckpt] shard publish of step {step} failed "
                  f"({type(e).__name__}: {e}); continuing", flush=True)

    def _maybe_publish(self, snap: snapshot_lib.Snapshot) -> None:
        if not getattr(self.cfg, "peer_fetch", True):
            return
        cap = getattr(self.cfg, "peer_publish_max_bytes", 64 << 20)
        if snap.nbytes() > cap:
            # Pre-filter on raw bytes (the npz payload is never smaller)
            # so over-cap models skip the whole serialize — otherwise
            # every persist of a big model would encode a full payload
            # only to discard it against the cap.
            return  # store-sized models only; disk + Orbax tiers remain
        store = self._get_store()
        if store is None:
            return
        payload = snapshot_lib.serialize_leaves(snap)
        if len(payload) > cap:
            return
        try:
            peer.publish(store, self.host, snapshot_lib.snapshot_meta(snap),
                         payload)
        except Exception as e:
            print(f"[ckpt] peer publish of step {snap.step} failed "
                  f"({type(e).__name__}: {e}); continuing", flush=True)

    # ------------------------------------------------------------------- gc
    def _gc(self) -> None:
        """Retention over BOTH hot tiers, coherent with the persistent
        tier: the newest manifest-verified persistent step and the
        newest sealed hot step are pinned — GC can never delete the
        state the next recovery would reach for. (The persistent tier
        itself ages under Orbax's max_to_keep, unchanged.)"""
        pins = set()
        try:
            verified = self.persistent.latest_good_step()
            if verified is not None:
                pins.add(int(verified))
        except Exception:
            pass
        keep_last = max(int(getattr(self.cfg, "hot_keep", 2)), 1)
        keep_every = int(getattr(self.cfg, "keep_every", 0))
        sealed = self.ram.sealed_steps()
        if sealed:
            pins.add(sealed[-1])
        for s in retention.plan_evictions(self.ram.steps(),
                                          keep_last=keep_last,
                                          keep_every=keep_every,
                                          pinned=pins):
            self.ram.evict(s)
        if self.disk is not None:
            disk_sealed = self.disk.sealed_steps()
            disk_pins = set(pins)
            if disk_sealed:
                disk_pins.add(disk_sealed[-1])
            for s in retention.plan_evictions(self.disk.steps(),
                                              keep_last=keep_last,
                                              keep_every=keep_every,
                                              pinned=disk_pins):
                self.disk.evict(s)

    # -------------------------------------------------------------- restore
    def _own_header(self, header: dict) -> bool:
        """Run identity for hot snapshots: the origin (persistent dir)
        must be THIS run's. Empty origin (hand-built snapshot in a
        test) is trusted — the guard targets reused scratch dirs."""
        origin = header.get("origin", "")
        return not origin or origin == self.dir

    def _disk_sealed_own(self) -> list[int]:
        if self.disk is None:
            return []
        return [s for s in self.disk.sealed_steps()
                if self._own_header(self.disk.header(s) or {})]

    def _peer_steps(self) -> list[int]:
        """Steps peers advertise on the KV store — a cross-host restart
        must see a snapshot that outlived its (dead) writer there, or a
        step=None resume would never reach the peer tier."""
        if not getattr(self.cfg, "peer_fetch", True):
            return []
        store = self._get_store()
        if store is None:
            return []
        try:
            return sorted(peer.advertised_steps(store, self._hosts())
                          .values())
        except Exception:
            return []

    def latest_step(self) -> int | None:
        cands = [self.persistent.latest_step()]
        cands += self.ram.sealed_steps()[-1:]
        cands += self._disk_sealed_own()[-1:]
        cands += self._peer_steps()[-1:]
        cands = [c for c in cands if c is not None]
        return max(cands) if cands else None

    def latest_good_step(self) -> int | None:
        """Newest restorable step across every tier: sealed hot
        snapshots are checksum-verified (this package's integrity),
        peer-advertised snapshots are CRC-verified at fetch time, and
        persistent steps are manifest-verified (faults/integrity.py).
        A candidate that fails its verification at restore time falls
        through to the next tier / the newest persistent step."""
        cands = [self.persistent.latest_good_step()]
        cands += self.ram.sealed_steps()[-1:]
        cands += self._disk_sealed_own()[-1:]
        cands += self._peer_steps()[-1:]
        cands = [c for c in cands if c is not None]
        return max(cands) if cands else None

    def _tier_counter(self, tier: str):
        return get_registry().counter(
            "ckpt_restore_tier_total", labels={"tier": tier},
            help="restores served, by tier (ram/disk/peer/orbax)")

    def _note_tier(self, tier: str, step) -> None:
        self._tier_counter(tier).inc()
        events_lib.emit("ckpt", "restore_tier", step=step, tier=tier)

    def _corrupt_counter(self):
        return get_registry().counter(
            "ckpt_hot_corrupt_total",
            help="hot-tier restore candidates that failed checksum/"
                 "structure verification and were fallen past")

    def restore(self, abstract_state, step: int | None = None):
        target = step
        if target is None:
            target = self.latest_good_step()
        if target is None:
            return None
        out = self._restore_hot(abstract_state, int(target))
        if out is not None:
            return out
        # Persistent fallback. The target may be hot-only (never
        # committed, or its persist died): restore the newest verified
        # persistent step instead of failing the resume.
        from pytorch_distributed_train_tpu.faults import integrity

        if not integrity.step_committed(self.dir, int(target)):
            fallback = self.persistent.latest_good_step()
            if fallback is None:
                return None
            if int(fallback) != int(target):
                print(f"[ckpt] step {target} unavailable in any tier; "
                      f"falling back to persistent step {fallback}",
                      flush=True)
            target = fallback
        restored = self.persistent.restore(abstract_state, step=int(target))
        if restored is not None:
            self._note_tier("orbax", int(target))
        return restored

    def _restore_hot(self, abstract_state, step: int):
        template = checkpoint_lib._savable(abstract_state)
        # --- RAM
        snap = self.ram.get(step)
        if snap is not None and snap.sealed:
            if snapshot_lib.verify(snap):
                out = self._place_tree(abstract_state, template, snap.tree,
                                       {"epoch": snap.epoch, **snap.meta})
                if out is not None:
                    self._note_tier("ram", step)
                    return out
            else:
                self._corrupt_counter().inc()
                print(f"[ckpt] RAM snapshot of step {step} failed "
                      "verification; trying the next tier", flush=True)
        # --- local disk
        if self.disk is not None:
            loaded = self.disk.load(step)  # None for absent OR corrupt
            if loaded is not None and not self._own_header(loaded[1]):
                # A node-local hot_dir outliving its run: matching
                # shapes/dtypes are NOT identity — never hand this run
                # another experiment's state.
                print(f"[ckpt] disk snapshot of step {step} belongs to "
                      f"run {loaded[1].get('origin')!r}, not "
                      f"{self.dir!r}; skipping the tier", flush=True)
            elif loaded is not None:
                leaves, header = loaded
                out = self._place_leaves(abstract_state, template, leaves,
                                         header)
                if out is not None:
                    self._note_tier("disk", step)
                    return out
            elif step in self.disk.steps():
                self._corrupt_counter().inc()
                print(f"[ckpt] disk snapshot of step {step} failed "
                      "verification; trying the next tier", flush=True)
        # --- peers
        out = self._restore_peer(abstract_state, template, step)
        if out is not None:
            self._note_tier("peer", step)
            return out
        return None

    def _restore_peer(self, abstract_state, template, step: int):
        if not getattr(self.cfg, "peer_fetch", True):
            return None
        store = self._get_store()
        if store is None:
            return None
        try:
            fetched = retry_lib.retry_call(
                lambda: peer.fetch_state(store, step, self._hosts()),
                point="ckpt.peer_fetch")
        except OSError as e:
            print(f"[ckpt] peer fetch of step {step} failed after "
                  f"retries ({type(e).__name__}: {e}); falling back to "
                  "persistent storage", flush=True)
            return None
        if fetched is None:
            return None
        kind, data, header = fetched
        if kind == "leaves":
            # shard publications, reassembled + CRC-verified by
            # peer.fetch_state (elastic reshard: the assembly is
            # mesh-agnostic, _place_leaves reshards into the template)
            return self._place_leaves(abstract_state, template, data,
                                      header)
        payload = data
        if not snapshot_lib.verify_payload(payload, header):
            self._corrupt_counter().inc()
            return None
        leaves = snapshot_lib.deserialize_leaves(payload)
        return self._place_leaves(abstract_state, template, leaves, header)

    # ------------------------------------------------------- placement glue
    def _place_leaves(self, abstract_state, template, leaves, header):
        t_leaves, treedef = jax.tree_util.tree_flatten(template)
        if not snapshot_lib.leaves_match_template(leaves, t_leaves):
            self._corrupt_counter().inc()
            return None
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        meta = {"epoch": int(header.get("epoch", 0)),
                **(header.get("meta") or {})}
        return self._place_tree(abstract_state, template, tree, meta)

    def _place_tree(self, abstract_state, template, tree, meta):
        """Host tree → device arrays in the template's shardings →
        rebuilt TrainState. None on structure mismatch (a checkpoint
        from a different config: fall through to Orbax, whose partial-
        template pruning handles cross-version resume)."""
        try:
            placed = jax.tree.map(
                lambda t, h: jax.device_put(h, getattr(t, "sharding", None)),
                template, tree)
            state = checkpoint_lib.apply_restored(abstract_state, placed)
        except (ValueError, TypeError, KeyError) as e:
            self._corrupt_counter().inc()
            print(f"[ckpt] hot snapshot does not match the live state "
                  f"structure ({type(e).__name__}: {e}); trying the next "
                  "tier", flush=True)
            return None
        return state, dict(meta)

    # ------------------------------------------------------------ passthru
    def read_meta(self, step: int | None = None) -> dict:
        return self.persistent.read_meta(step)

    def steps_by_tier(self) -> dict[str, list[int]]:
        out = {"ram": self.ram.sealed_steps(),
               "disk": self.disk.sealed_steps() if self.disk else [],
               "persistent": []}
        try:
            out["persistent"] = sorted(
                int(s) for s in self.persistent.mgr.all_steps())
        except Exception:
            pass
        return out

    def wait(self) -> None:
        """Drain the in-flight persist (re-raising its terminal error —
        a force-save caller must know its checkpoint didn't land), then
        finalize manifests."""
        with span("checkpoint.wait"):
            self.persister.drain()
        self.persistent.wait()

    def close(self) -> None:
        try:
            self.persister.stop()
        except Exception as e:
            print(f"[ckpt] persister stop: {type(e).__name__}: {e}",
                  flush=True)
        self.persistent.close()
        if self._store is not None:
            try:
                self._store.close()
            except Exception:
                pass
            self._store = None


def build_checkpoint_manager(ckpt_cfg, config_json: str = "", *,
                             goodput=None, run_meta: dict | None = None):
    """``checkpoint.tiered`` selects the plane; every caller (trainer,
    tools) goes through here so the flag is the only divergence point."""
    if getattr(ckpt_cfg, "tiered", False):
        return TieredCheckpointManager(ckpt_cfg, config_json,
                                       goodput=goodput, run_meta=run_meta)
    return checkpoint_lib.CheckpointManager(ckpt_cfg, config_json,
                                            run_meta=run_meta)
