"""Background persister: one in-flight persist, explicit back-pressure.

The step loop hands a freshly taken snapshot to ``submit()`` and keeps
training; this thread runs the persist pipeline (seal → disk spill →
peer publish → Orbax write + manifest → retention GC, assembled by
ckpt/manager.py) against the immutable host copy.

At most ONE persist is in flight. If the next save boundary arrives
while the previous persist is still writing, the caller must ``drain()``
first — that wait is the back-pressure signal (the ``ckpt.drain``
goodput bucket): persistent storage is slower than the save cadence,
and hiding that by queueing snapshots would grow host RAM until OOM at
exactly the moment (degraded storage) it matters most.

A persist that raises is terminal for that snapshot: the error is
printed and counted (``ckpt_persist_failures_total``), the snapshot is
marked ``persist_failed`` (it remains a valid hot restore source — the
arrays are intact), and the persister stays alive for the next submit.
The exception is also re-raised to the next ``drain()``/``stop()``
caller so a synchronous save boundary (final force-save, preemption)
still escalates instead of silently losing the job's last checkpoint.
"""

from __future__ import annotations

import threading
import time


class Persister:
    def __init__(self, name: str = "ckpt-persister"):
        self._cond = threading.Condition()
        self._job = None            # (snapshot, callable) or None
        self._busy = False
        self._stopping = False
        self._error: BaseException | None = None
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- callers
    @property
    def busy(self) -> bool:
        with self._cond:
            return self._busy or self._job is not None

    def submit(self, snap, job) -> None:
        """Hand (snapshot, job-callable) to the thread. The caller must
        have drained first; submitting over an in-flight persist raises
        — the single-slot invariant is the whole point."""
        with self._cond:
            if self._stopping:
                raise RuntimeError("persister is stopped")
            if self._busy or self._job is not None:
                raise RuntimeError(
                    "persist already in flight — drain() before submit()")
            # A new persist supersedes the previous one's outcome: an
            # undrained terminal error from an EARLIER snapshot must not
            # lie in wait for hours and then poison an unrelated
            # drain()/wait() caller (it was already printed + counted);
            # drain() reports only the MOST RECENT persist's failure.
            self._error = None
            self._job = (snap, job)
            self._cond.notify_all()

    def drain(self, timeout: float | None = None) -> float:
        """Block until no persist is in flight; returns seconds waited.
        Re-raises a terminal persist error exactly once (see module
        docstring)."""
        t0 = time.perf_counter()
        with self._cond:
            while self._busy or self._job is not None:
                if not self._cond.wait(timeout=timeout):
                    raise TimeoutError(
                        f"persist did not drain within {timeout}s")
            err, self._error = self._error, None
        if err is not None:
            raise err
        return time.perf_counter() - t0

    def stop(self, timeout: float = 60.0) -> None:
        """Drain and join. Errors from the last persist propagate."""
        try:
            self.drain(timeout=timeout)
        finally:
            with self._cond:
                self._stopping = True
                self._cond.notify_all()
            self._thread.join(timeout=timeout)

    # -------------------------------------------------------------- thread
    def _run(self) -> None:
        while True:
            with self._cond:
                while self._job is None and not self._stopping:
                    self._cond.wait()
                if self._job is None and self._stopping:
                    return
                snap, job = self._job
                self._job = None
                self._busy = True
            try:
                job(snap)
            except BaseException as e:  # noqa: BLE001 — must not die
                snap.persist_failed = True
                print(f"[ckpt] background persist of step {snap.step} "
                      f"FAILED ({type(e).__name__}: {e}); newest sealed "
                      "hot snapshot remains the restore source",
                      flush=True)
                from pytorch_distributed_train_tpu.obs.registry import (
                    get_registry,
                )

                get_registry().counter(
                    "ckpt_persist_failures_total",
                    help="background checkpoint persists that failed "
                         "terminally (snapshot stays hot-restorable)").inc()
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
