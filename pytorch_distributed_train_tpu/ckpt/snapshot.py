"""Device→host snapshots: the blocking half of an async checkpoint.

A tiered save (ckpt/manager.py) splits a checkpoint into two phases:

1. **snapshot** — copy the live state's device arrays into host RAM.
   This is the only part the step loop waits for (``ckpt_blocking_ms``);
   it is bounded by HBM→host bandwidth, not by persistent-storage I/O.
2. **persist** — everything after the copy (seal, local-disk spill, peer
   publish, the Orbax write + manifest) runs on a background thread
   against the immutable host copy while training continues.

A ``Snapshot`` becomes **sealed** once per-leaf CRCs are computed over
the host arrays (ckpt/persister.py does this first, before any I/O):
sealed snapshots are what the hot tier may serve on restore, and the
CRCs are what lets a restore distinguish "hot copy intact" from "hot
copy corrupt, fall back a tier".

Serialization (disk spill / peer transfer) is leaf-ordered: the restorer
always holds an abstract template of the state it wants (the trainer's
live TrainState), so the wire format carries only the ordered flattened
leaves plus a JSON meta block — the template's treedef rebuilds the
structure, and any template/payload mismatch is detected by leaf count/
shape/dtype instead of trusting a pickled treedef.

Single-controller caveat: ``take_snapshot`` gathers each array with
``np.asarray``, which requires the arrays to be fully addressable from
this process (true for single-host jobs and for per-process test
workers). A multi-host GSPMD job whose arrays span hosts falls back to
the synchronous Orbax path (ckpt/manager.py catches the error) — per-
shard host snapshots are the documented follow-up, not silently wrong
data.
"""

from __future__ import annotations

import dataclasses
import io
import json
import time
import zlib

import jax
import numpy as np


@dataclasses.dataclass
class Snapshot:
    """One host-RAM copy of a savable state tree (checkpoint._savable
    layout: plain dict of params/opt_state/... with array leaves)."""

    step: int
    epoch: int
    tree: dict
    meta: dict = dataclasses.field(default_factory=dict)
    # Which run this snapshot belongs to (the persistent checkpoint
    # dir): a node-local hot_dir outliving its run must not hand a NEW
    # experiment the old one's state just because shapes/dtypes match —
    # restore compares this against its own dir (ckpt/manager.py).
    origin: str = ""
    created_at: float = dataclasses.field(default_factory=time.time)
    # leaf CRCs in flatten order, computed at seal time (persister
    # thread — off the step loop's critical path)
    checksums: tuple[int, ...] | None = None
    sealed: bool = False
    # the background Orbax persist for this snapshot failed terminally:
    # the snapshot is still a valid restore source (the arrays are
    # intact), but the step never became a committed persistent step
    persist_failed: bool = False

    def nbytes(self) -> int:
        return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(
            self.tree))


def take_snapshot(savable: dict, *, step: int, epoch: int = 0,
                  meta: dict | None = None, origin: str = "") -> Snapshot:
    """Blocking device→host copy of a ``checkpoint._savable`` dict.

    ``np.asarray`` waits for in-flight computation producing each leaf
    and then copies it out — the whole step-boundary cost of an async
    save. Leaves already on host (numpy) are copied too: the snapshot
    must be immutable while the persister works on it."""
    tree = jax.tree.map(lambda x: np.array(jax.device_get(x)), savable)
    return Snapshot(step=int(step), epoch=int(epoch), tree=tree,
                    meta=dict(meta or {}), origin=origin)


def _leaf_crc(leaf: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(leaf).tobytes())


def seal(snap: Snapshot) -> Snapshot:
    """Compute per-leaf CRCs and mark the snapshot sealed. RAM-bandwidth
    work (no I/O) — the persister runs it before any persistence so the
    hot tier gains a verified restore source within milliseconds of the
    save boundary."""
    leaves = jax.tree_util.tree_leaves(snap.tree)
    snap.checksums = tuple(_leaf_crc(leaf) for leaf in leaves)
    snap.sealed = True
    return snap


def verify(snap: Snapshot) -> bool:
    """Recompute leaf CRCs against the seal — False for unsealed or
    corrupted-in-RAM snapshots (the caller falls back a tier)."""
    if not snap.sealed or snap.checksums is None:
        return False
    leaves = jax.tree_util.tree_leaves(snap.tree)
    if len(leaves) != len(snap.checksums):
        return False
    return all(_leaf_crc(leaf) == crc
               for leaf, crc in zip(leaves, snap.checksums))


# ------------------------------------------------------------- wire format
def snapshot_meta(snap: Snapshot) -> dict:
    """The JSON-serializable header that travels with the leaves (disk
    meta.json / peer store meta key)."""
    return {
        "step": snap.step,
        "epoch": snap.epoch,
        "meta": snap.meta,
        "origin": snap.origin,
        "created_at": snap.created_at,
        "checksums": list(snap.checksums or ()),
        "sealed": bool(snap.sealed),
    }


def serialize_leaves(snap: Snapshot) -> bytes:
    """Flatten-order ``.npz`` of the snapshot's leaves (``leaf_<i>``
    keys). Structure is NOT serialized — the restorer's template
    supplies it (see module docstring)."""
    leaves = jax.tree_util.tree_leaves(snap.tree)
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)})
    return buf.getvalue()


def deserialize_leaves(payload: bytes) -> list[np.ndarray]:
    with np.load(io.BytesIO(payload)) as z:
        return [z[f"leaf_{i}"] for i in range(len(z.files))]


def leaves_match_template(leaves: list, template_leaves: list) -> bool:
    """Count + shape + dtype agreement — the precondition for
    unflattening foreign leaves with the template's treedef."""
    if len(leaves) != len(template_leaves):
        return False
    for got, want in zip(leaves, template_leaves):
        if tuple(got.shape) != tuple(want.shape):
            return False
        if np.dtype(got.dtype) != np.dtype(want.dtype):
            return False
    return True


def verify_payload(payload: bytes, header: dict) -> bool:
    """Header CRCs vs the deserialized leaves (disk/peer integrity)."""
    if not header.get("sealed"):
        return False
    crcs = header.get("checksums") or []
    try:
        leaves = deserialize_leaves(payload)
    except Exception:
        return False
    if len(leaves) != len(crcs):
        return False
    return all(_leaf_crc(leaf) == crc for leaf, crc in zip(leaves, crcs))


def header_json(snap: Snapshot) -> bytes:
    return json.dumps(snapshot_meta(snap), sort_keys=True).encode()
