"""Device→host snapshots: the blocking half of an async checkpoint.

A tiered save (ckpt/manager.py) splits a checkpoint into two phases:

1. **snapshot** — copy the live state's device arrays into host RAM.
   This is the only part the step loop waits for (``ckpt_blocking_ms``);
   it is bounded by HBM→host bandwidth, not by persistent-storage I/O.
2. **persist** — everything after the copy (seal, local-disk spill, peer
   publish, the Orbax write + manifest) runs on a background thread
   against the immutable host copy while training continues.

A ``Snapshot`` becomes **sealed** once per-leaf CRCs are computed over
the host arrays (ckpt/persister.py does this first, before any I/O):
sealed snapshots are what the hot tier may serve on restore, and the
CRCs are what lets a restore distinguish "hot copy intact" from "hot
copy corrupt, fall back a tier".

Serialization (disk spill / peer transfer) is leaf-ordered: the restorer
always holds an abstract template of the state it wants (the trainer's
live TrainState), so the wire format carries only the ordered flattened
leaves plus a JSON meta block — the template's treedef rebuilds the
structure, and any template/payload mismatch is detected by leaf count/
shape/dtype instead of trusting a pickled treedef.

Single-controller caveat: ``take_snapshot`` gathers each array with
``np.asarray``, which requires the arrays to be fully addressable from
this process (true for single-host jobs and for per-process test
workers). A multi-host GSPMD job whose arrays span hosts falls back to
the synchronous Orbax path (ckpt/manager.py catches the error) — per-
shard host snapshots are the documented follow-up, not silently wrong
data.
"""

from __future__ import annotations

import dataclasses
import io
import json
import time
import zlib

import jax
import numpy as np


@dataclasses.dataclass
class Snapshot:
    """One host-RAM copy of a savable state tree (checkpoint._savable
    layout: plain dict of params/opt_state/... with array leaves)."""

    step: int
    epoch: int
    tree: dict
    meta: dict = dataclasses.field(default_factory=dict)
    # Which run this snapshot belongs to (the persistent checkpoint
    # dir): a node-local hot_dir outliving its run must not hand a NEW
    # experiment the old one's state just because shapes/dtypes match —
    # restore compares this against its own dir (ckpt/manager.py).
    origin: str = ""
    created_at: float = dataclasses.field(default_factory=time.time)
    # leaf CRCs in flatten order, computed at seal time (persister
    # thread — off the step loop's critical path)
    checksums: tuple[int, ...] | None = None
    sealed: bool = False
    # the background Orbax persist for this snapshot failed terminally:
    # the snapshot is still a valid restore source (the arrays are
    # intact), but the step never became a committed persistent step
    persist_failed: bool = False

    def nbytes(self) -> int:
        return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(
            self.tree))


def take_snapshot(savable: dict, *, step: int, epoch: int = 0,
                  meta: dict | None = None, origin: str = "") -> Snapshot:
    """Blocking device→host copy of a ``checkpoint._savable`` dict.

    ``np.asarray`` waits for in-flight computation producing each leaf
    and then copies it out — the whole step-boundary cost of an async
    save. Leaves already on host (numpy) are copied too: the snapshot
    must be immutable while the persister works on it."""
    tree = jax.tree.map(lambda x: np.array(jax.device_get(x)), savable)
    return Snapshot(step=int(step), epoch=int(epoch), tree=tree,
                    meta=dict(meta or {}), origin=origin)


def _leaf_crc(leaf: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(leaf).tobytes())


def seal(snap: Snapshot) -> Snapshot:
    """Compute per-leaf CRCs and mark the snapshot sealed. RAM-bandwidth
    work (no I/O) — the persister runs it before any persistence so the
    hot tier gains a verified restore source within milliseconds of the
    save boundary."""
    leaves = jax.tree_util.tree_leaves(snap.tree)
    snap.checksums = tuple(_leaf_crc(leaf) for leaf in leaves)
    snap.sealed = True
    return snap


def verify(snap: Snapshot) -> bool:
    """Recompute leaf CRCs against the seal — False for unsealed or
    corrupted-in-RAM snapshots (the caller falls back a tier)."""
    if not snap.sealed or snap.checksums is None:
        return False
    leaves = jax.tree_util.tree_leaves(snap.tree)
    if len(leaves) != len(snap.checksums):
        return False
    return all(_leaf_crc(leaf) == crc
               for leaf, crc in zip(leaves, snap.checksums))


# ------------------------------------------------------------- wire format
def snapshot_meta(snap: Snapshot) -> dict:
    """The JSON-serializable header that travels with the leaves (disk
    meta.json / peer store meta key)."""
    return {
        "step": snap.step,
        "epoch": snap.epoch,
        "meta": snap.meta,
        "origin": snap.origin,
        "created_at": snap.created_at,
        "checksums": list(snap.checksums or ()),
        "sealed": bool(snap.sealed),
    }


def serialize_leaves(snap: Snapshot) -> bytes:
    """Flatten-order ``.npz`` of the snapshot's leaves (``leaf_<i>``
    keys). Structure is NOT serialized — the restorer's template
    supplies it (see module docstring)."""
    leaves = jax.tree_util.tree_leaves(snap.tree)
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)})
    return buf.getvalue()


def deserialize_leaves(payload: bytes) -> list[np.ndarray]:
    with np.load(io.BytesIO(payload)) as z:
        return [z[f"leaf_{i}"] for i in range(len(z.files))]


def leaves_match_template(leaves: list, template_leaves: list) -> bool:
    """Count + shape + dtype agreement — the precondition for
    unflattening foreign leaves with the template's treedef."""
    if len(leaves) != len(template_leaves):
        return False
    for got, want in zip(leaves, template_leaves):
        if tuple(got.shape) != tuple(want.shape):
            return False
        if np.dtype(got.dtype) != np.dtype(want.dtype):
            return False
    return True


def verify_payload(payload: bytes, header: dict) -> bool:
    """Header CRCs vs the deserialized leaves (disk/peer integrity)."""
    if not header.get("sealed"):
        return False
    crcs = header.get("checksums") or []
    try:
        leaves = deserialize_leaves(payload)
    except Exception:
        return False
    if len(leaves) != len(crcs):
        return False
    return all(_leaf_crc(leaf) == crc for leaf, crc in zip(leaves, crcs))


def header_json(snap: Snapshot) -> bytes:
    return json.dumps(snapshot_meta(snap), sort_keys=True).encode()


# ----------------------------------------------------- shard wire format
#
# Elastic resharding (docs/elastic.md): a multi-host GSPMD job's arrays
# span hosts, so no single host can take (or publish) the full-leaf
# snapshot above. Instead each host ships only the array shards it OWNS
# (addressable + replica_id 0 — exactly one owner per global element),
# and a restoring host reassembles the GLOBAL leaves from every host's
# payload — including a dead host's, whose chunks outlive it on the
# launcher store — then device_puts them into the NEW mesh's shardings.
# Wire layout: ``part_<k>`` npz entries plus a header carrying
#
#     shard_format: 1
#     leaves:  [{shape, dtype}, ...]          # global, flatten order
#     parts:   [{leaf, start, crc}, ...]      # this payload's pieces
#
# Assembly verifies per-part CRCs and full element coverage — a missing
# host reads as "incomplete", never as silently-zeroed state.


def owned_shard_nbytes(savable: dict, owned=None) -> int:
    """Raw bytes ``take_shard_snapshot`` would copy host-side for THIS
    host — the npz payload is never smaller, so callers pre-filter the
    publish cap on it WITHOUT paying the device→host copies + encode
    (``.nbytes`` on a device shard is metadata, not a transfer)."""
    if owned is None:
        owned = lambda shard: shard.replica_id == 0  # noqa: E731
    total = 0
    for leaf in jax.tree_util.tree_leaves(savable):
        if hasattr(leaf, "addressable_shards"):
            total += sum(int(s.data.nbytes)
                         for s in leaf.addressable_shards if owned(s))
        else:
            total += int(np.asarray(leaf).nbytes)
    return total


def take_shard_snapshot(savable: dict, *, step: int, epoch: int = 0,
                        meta: dict | None = None, origin: str = "",
                        owned=None) -> tuple[bytes, dict]:
    """(payload, sealed header) holding THIS host's owned shards of a
    ``checkpoint._savable`` dict. ``owned`` overrides the ownership
    predicate (tests simulate hosts by partitioning device ids);
    the default owns addressable replica-0 shards."""
    if owned is None:
        owned = lambda shard: shard.replica_id == 0  # noqa: E731
    leaves = jax.tree_util.tree_leaves(savable)
    index: list[dict] = []
    shapes: list[dict] = []
    parts: list[np.ndarray] = []
    for i, leaf in enumerate(leaves):
        shapes.append({"shape": list(getattr(leaf, "shape", ())),
                       "dtype": str(np.dtype(leaf.dtype))})
        if hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if not owned(shard):
                    continue
                data = np.asarray(shard.data)
                start = [0 if s.start is None else int(s.start)
                         for s in shard.index]
                start += [0] * (data.ndim - len(start))
                parts.append(data)
                index.append({"leaf": i, "start": start,
                              "crc": _leaf_crc(data)})
        else:
            # host-resident leaf (numpy/scalar): one full-cover part —
            # every publisher owns it; assembly tolerates identical
            # overlap via the coverage mask
            data = np.asarray(leaf)
            parts.append(data)
            index.append({"leaf": i, "start": [0] * data.ndim,
                          "crc": _leaf_crc(data)})
    buf = io.BytesIO()
    np.savez(buf, **{f"part_{k}": p for k, p in enumerate(parts)})
    header = {
        "step": int(step), "epoch": int(epoch), "meta": dict(meta or {}),
        "origin": origin, "created_at": time.time(), "sealed": True,
        "shard_format": 1, "leaves": shapes, "parts": index,
    }
    return buf.getvalue(), header


def verify_shard_payload(payload: bytes, header: dict) -> bool:
    """Per-part CRC check of one host's shard payload."""
    if not header.get("sealed") or header.get("shard_format") != 1:
        return False
    try:
        with np.load(io.BytesIO(payload)) as z:
            parts = [z[f"part_{k}"] for k in range(len(z.files))]
    except Exception:
        return False
    idx = header.get("parts") or []
    if len(parts) != len(idx):
        return False
    return all(_leaf_crc(p) == rec["crc"] for p, rec in zip(parts, idx))


def assemble_shards(fetched: list[tuple[bytes, dict]]
                    ) -> tuple[list[np.ndarray], dict] | None:
    """Rebuild GLOBAL flatten-order leaves from every host's (payload,
    header). None when headers disagree, any part fails its CRC, or
    coverage is incomplete (a host's shards are missing and nobody else
    owned those elements) — the caller falls back a tier."""
    if not fetched:
        return None
    ref = fetched[0][1]
    shapes = ref.get("leaves") or []
    if not shapes or ref.get("shard_format") != 1:
        return None
    leaves = [np.zeros(tuple(s["shape"]), np.dtype(s["dtype"]))
              for s in shapes]
    masks = [np.zeros(tuple(s["shape"]), bool) for s in shapes]
    for payload, header in fetched:
        if (header.get("shard_format") != 1
                or header.get("leaves") != shapes
                or header.get("step") != ref.get("step")):
            return None
        if not verify_shard_payload(payload, header):
            return None
        with np.load(io.BytesIO(payload)) as z:
            parts = [z[f"part_{k}"] for k in range(len(z.files))]
        for part, rec in zip(parts, header["parts"]):
            i = int(rec["leaf"])
            if not 0 <= i < len(leaves):
                return None
            sl = tuple(slice(s, s + n)
                       for s, n in zip(rec["start"], part.shape))
            if part.ndim != leaves[i].ndim:
                if part.ndim == 0 and leaves[i].ndim == 0:
                    sl = ()
                else:
                    return None
            try:
                leaves[i][sl] = part
                masks[i][sl] = True
            except (ValueError, IndexError):
                return None
    if not all(m.all() for m in masks):
        return None  # incomplete coverage: someone's shards are missing
    return leaves, dict(ref)
