"""Tiered asynchronous checkpointing plane (docs/checkpointing.md).

Splits every checkpoint into a blocking device→host snapshot and a
background persist, keeps the last K sealed snapshots hot (host RAM +
per-host local disk), exchanges snapshots between hosts over the
launcher's KV store, and garbage-collects all tiers under one retention
policy. ``build_checkpoint_manager`` is the entry point; the
``checkpoint.tiered`` config flag selects this plane over the plain
Orbax-backed ``CheckpointManager``.
"""

from pytorch_distributed_train_tpu.ckpt.manager import (  # noqa: F401
    TieredCheckpointManager,
    build_checkpoint_manager,
)
