"""Peer snapshot exchange over the launcher's KV store.

Cross-host restart path: a worker respawned on a DIFFERENT host has an
empty RAM tier and someone else's disk tier, but its peers (or the
launcher outliving the workers) may still hold the newest sealed
snapshot. Rather than round-tripping through persistent storage, each
host publishes its newest sealed snapshot to the rendezvous store
(native/store.cpp — the same KV plane elastic.py and the liveness
sentinel already ride), and a restoring worker fetches it chunk by
chunk before falling back to Orbax.

Wire layout (all keys under one namespace)::

    ckptp/<host>/meta            JSON: snapshot header + chunking info
    ckptp/<host>/<step>/c<i>     payload chunks (<= CHUNK_BYTES each)

Chunks are written BEFORE the meta key: a reader that sees meta can
read every chunk it names (the store has no transactions; ordering is
the atomicity). Only the newest sealed step is published per host —
the previous step's chunks are deleted after the new meta lands, so
store memory stays bounded at ~one snapshot per host.

This plane is for models whose per-host snapshot fits comfortably in
the store (``checkpoint.peer_publish_max_bytes`` gates publication);
a 7B-scale run keeps the disk + Orbax tiers and simply never
publishes. The ``ckpt.peer_fetch`` fault point injects transport
errors into the fetch path; exhausted retries fall back to Orbax,
never fail the restore.
"""

from __future__ import annotations

import json
import zlib

from pytorch_distributed_train_tpu.faults import registry as faults_registry

CHUNK_BYTES = 512 * 1024  # store get() buffers default to 1 MiB
_NS = "ckptp"


def _meta_key(host: int) -> str:
    return f"{_NS}/{int(host)}/meta"


def _chunk_key(host: int, step: int, i: int) -> str:
    return f"{_NS}/{int(host)}/{int(step)}/c{int(i)}"


def publish(store, host: int, header: dict, payload: bytes,
            chunk_bytes: int = CHUNK_BYTES) -> None:
    """Publish (header, payload) as this host's newest sealed snapshot,
    replacing (and then deleting) the previously published step."""
    prev = None
    try:
        prev = json.loads(store.get(_meta_key(host), timeout_ms=1).decode())
    except Exception:
        prev = None  # nothing published yet
    n_chunks = max(1, (len(payload) + chunk_bytes - 1) // chunk_bytes)
    step = int(header["step"])
    for i in range(n_chunks):
        store.set(_chunk_key(host, step, i),
                  payload[i * chunk_bytes:(i + 1) * chunk_bytes])
    meta = dict(header)
    meta.update(n_chunks=n_chunks, payload_bytes=len(payload),
                payload_crc32=zlib.crc32(payload))
    store.set(_meta_key(host), json.dumps(meta, sort_keys=True).encode())
    if prev is not None and int(prev.get("step", -1)) != step:
        for i in range(int(prev.get("n_chunks", 0))):
            try:
                store.delete(_chunk_key(host, int(prev["step"]), i))
            except Exception:
                pass  # best-effort housekeeping
    from pytorch_distributed_train_tpu.obs.registry import get_registry

    get_registry().gauge(
        "ckpt_peer_published_step",
        help="newest snapshot step this host has published to the "
             "peer store").set(step)


def _fetch_host(store, host: int, step: int,
                chunk_timeout_ms: int) -> tuple[bytes, dict] | None:
    """One host's (payload, header) for ``step`` — complete and
    chunk-consistent — or None. CRC-verified end to end; a corrupt
    transfer reads as "not found"."""
    try:
        meta = json.loads(
            store.get(_meta_key(host), timeout_ms=50).decode())
    except Exception:
        return None  # host never published / key expired with the store
    if int(meta.get("step", -1)) != int(step) or not meta.get("sealed"):
        return None
    chunks = []
    try:
        for i in range(int(meta["n_chunks"])):
            chunks.append(store.get(_chunk_key(host, step, i),
                                    timeout_ms=chunk_timeout_ms))
    except Exception:
        return None  # racing a re-publish
    payload = b"".join(chunks)
    if (len(payload) != int(meta["payload_bytes"])
            or zlib.crc32(payload) != int(meta["payload_crc32"])):
        return None
    return payload, meta


def advertised_steps(store, hosts) -> dict[int, int]:
    """host → published step, for every peer with a meta key (the
    inspector tool and restore-target selection read this)."""
    out: dict[int, int] = {}
    for host in hosts:
        try:
            meta = json.loads(
                store.get(_meta_key(host), timeout_ms=50).decode())
            out[int(host)] = int(meta["step"])
        except Exception:
            continue
    return out


def fetch_state(store, step: int, hosts, *,
                chunk_timeout_ms: int = 10_000):
    """Restore-side entry for the elastic-reshard plane: the newest
    publication of ``step``, whatever its wire format.

    Returns ``("full", payload, header)`` when any host published the
    whole-leaves snapshot (single-host-addressable jobs — the common
    case; the FIRST verified full payload returns immediately, one
    host's download), or ``("leaves", leaves, header)`` when hosts
    published SHARD payloads (multi-host GSPMD): every advertising
    host's pieces — including a dead host's, whose chunks outlive it on
    the store — are CRC-verified and reassembled into global
    flatten-order leaves, ready to device_put into ANY mesh's
    shardings. None when neither path yields a complete, verified
    state."""
    from pytorch_distributed_train_tpu.ckpt import snapshot as snapshot_lib

    faults_registry.maybe_fire("ckpt.peer_fetch", step=step)
    shard_payloads = []
    for host in hosts:
        got = _fetch_host(store, host, step, chunk_timeout_ms)
        if got is None:
            continue
        payload, header = got
        if header.get("shard_format") == 1:
            shard_payloads.append((payload, header))
        else:
            return "full", payload, header
    if shard_payloads:
        assembled = snapshot_lib.assemble_shards(shard_payloads)
        if assembled is not None:
            return "leaves", assembled[0], assembled[1]
    return None
