"""Closed-loop fleet controller: alerts in, actuation out.

The reconciling control loop ROADMAP item 3 names: each tick reads
the fleet's diagnosis (AlertEngine firing state + FleetCollector load
rows) and drives the actuators the serving planes already have —
spawn a replica (pluggable :class:`ReplicaLauncher`), drain one
through serve_http's ``/admin/drain``, push router dispatch weights —
so a flash crowd or a sick host is *handled*, not just observed.

The action catalog is CLOSED (``ACTIONS``, mirrored by the table in
docs/autoscaler.md; the ``action-catalog`` analyze pass keeps the two
in sync both ways):

- ``scale_out`` — sustained ``shed_storm`` / ``ttft_regression`` /
  fast serving burn alerts: launch a replica, verify it answers
  /healthz, roll back (kill it) if it never does.
- ``scale_in``  — a calm fleet above ``min_replicas``: drain the
  least-loaded replica with zero failed requests (the router fails
  over around a draining replica by construction).
- ``recycle``   — ``host_oom_risk`` / ``restart_churn`` on a serving
  host: drain the sick replica and launch a replacement.
- ``rebalance`` — continuous policy: per-replica dispatch weights from
  queue depth + admission state, pushed through the router weights
  hook (``ReplicaSet.set_weights`` / ``POST /admin/weights``).

Safety rails are the point, not an afterthought:

- **bounds** — the fleet never leaves [min_replicas, max_replicas];
- **hysteresis** — an action needs its trigger across N consecutive
  evaluations, one spike is not a signal;
- **cooldowns** — per-action monotonic cooldowns bound act churn;
- **action budget** — at most ``budget_max_actions`` acts per rolling
  ``budget_window_s``; overflow LATCHES the controller into a loudly
  journaled ``degraded (budget_exhausted)`` observe-only mode (a
  controller in a tight act loop is itself the incident) until an
  operator calls :meth:`FleetController.reset_budget`;
- **store hold** — while the launcher-store health machine
  (store_plane, via ``collector.store_health()``) reports
  degraded/down, the controller holds a ``degraded (store)``
  observe-only mode: its fleet view rides registries the dead store
  can't refresh, so acting on it risks draining healthy replicas it
  merely can't see. Auto-clears on recovery (unlike the budget
  latch); every suppressed decision journals requested → skipped;
- **dry run** — journals every intended action, acts on nothing.

Every decision is journaled under the closed ``action`` event
category with a durable action id (``act-<action>-<epoch_ms>-<seq>``)
cross-linked to the triggering alert's incident id, through the
lifecycle ``requested → acting → effective | failed | rolled_back``
(plus ``skipped`` for rail-suppressed acts and ``mode`` for latch
transitions). ``faults.maybe_fire("controller.act")`` runs at every
actuation start, so action failure handling is drillable.

Deadlines/cooldowns ride ``time.monotonic()``; wall-clock appears
only in ids and journal timestamps. Stdlib + the repo's obs/faults
packages; no jax (runs on a login host).
"""

from __future__ import annotations

import dataclasses
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

from pytorch_distributed_train_tpu.faults import registry as fregistry
from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry

# closed outcome vocabulary: every journaled ``action`` lifecycle name
# except the mode latch; the action-catalog pass lints each action's
# declared outcomes against this set
OUTCOMES = ("requested", "acting", "effective", "failed",
            "rolled_back", "skipped")

# trigger sentinels that are policies, not alert rules
POLICY_TRIGGERS = ("calm", "policy")


@dataclasses.dataclass(frozen=True)
class ActionSpec:
    """One declared controller action. ``triggers`` name alert rules
    (obs/alerts.py RULES) or a policy sentinel ("calm"/"policy");
    ``outcomes`` are the terminal lifecycle names this action can
    journal (always through requested → acting first)."""

    name: str
    triggers: tuple
    actuator: str
    outcomes: tuple
    description: str


# The CLOSED catalog — docs/autoscaler.md '## Action catalog' mirrors
# this table; tools/analyze's action-catalog pass keeps the two in
# sync both ways.
ACTIONS: dict[str, ActionSpec] = {a.name: a for a in (
    ActionSpec(
        name="scale_out",
        triggers=("shed_storm", "ttft_regression",
                  "slo_serve_ttft_p95_burn_fast",
                  "slo_serve_availability_burn_fast"),
        actuator="ReplicaLauncher.launch (serve_http --advertise)",
        outcomes=("requested", "acting", "effective", "failed",
                  "rolled_back", "skipped"),
        description="sustained overload on the serving fleet: launch "
                    "one replica, verify /healthz answers, kill it if "
                    "it never does (rolled_back)"),
    ActionSpec(
        name="scale_in",
        triggers=("calm",),
        actuator="POST /admin/drain on the least-loaded replica",
        outcomes=("requested", "acting", "effective", "failed",
                  "skipped"),
        description="calm fleet above min_replicas: drain the least-"
                    "loaded replica gracefully — zero failed requests "
                    "by the drain + router-failover contract"),
    ActionSpec(
        name="recycle",
        triggers=("host_oom_risk", "restart_churn"),
        actuator="drain the sick replica, then ReplicaLauncher.launch",
        outcomes=("requested", "acting", "effective", "failed",
                  "skipped"),
        description="a serving host diagnosed sick: drain its replica "
                    "and launch a fresh one elsewhere"),
    ActionSpec(
        name="rebalance",
        triggers=("policy",),
        actuator="router weights hook (set_weights / POST "
                 "/admin/weights)",
        outcomes=("requested", "acting", "effective", "failed",
                  "skipped"),
        description="continuous load policy: dispatch weights from "
                    "per-replica queue depth + admission state, "
                    "pushed when they materially change"),
)}

# controller_mode gauge encoding
_MODE_VALUES = {"active": 0.0, "dry_run": 1.0,
                "degraded (budget_exhausted)": 2.0,
                "degraded (store)": 3.0}


class ReplicaLauncher:
    """Scale-out actuator interface: ``launch()`` returns the new
    replica's routable ``host:port`` (or None on failure); ``stop``
    reverses an unverifiable launch (the rollback path)."""

    def launch(self) -> str | None:  # pragma: no cover - interface
        raise NotImplementedError

    def stop(self, addr: str) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class SubprocessReplicaLauncher(ReplicaLauncher):
    """The drill/test launcher: spawn ``serve_http --fake-backend
    --advertise`` as a subprocess and parse its bound address off
    stdout. ``extra_args``/``env`` parameterize slots, delays and the
    store/journal env contract."""

    def __init__(self, *, python: str | None = None,
                 serve_http_path: str = "tools/serve_http.py",
                 extra_args: tuple = (), env: dict | None = None,
                 start_timeout_s: float = 20.0):
        self.python = python or sys.executable
        self.serve_http_path = serve_http_path
        self.extra_args = tuple(extra_args)
        self.env = env
        self.start_timeout_s = start_timeout_s
        self.procs: dict[str, subprocess.Popen] = {}

    def launch(self) -> str | None:
        cmd = [self.python, self.serve_http_path, "--fake-backend",
               "--port", "0", "--advertise", *self.extra_args]
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=self.env)
        addr = None
        deadline = time.monotonic() + self.start_timeout_s
        while time.monotonic() < deadline:
            line = proc.stdout.readline() if proc.stdout else ""
            if not line:
                if proc.poll() is not None:
                    break
                continue
            if line.startswith("serving on http://"):
                addr = line.split("http://", 1)[1].split()[0].strip("/")
                break
        if addr is None:
            try:
                proc.kill()
            except OSError:
                pass
            return None
        # drain the pipe so the child never blocks on a full stdout
        threading.Thread(target=self._pump, args=(proc,),
                         daemon=True,
                         name=f"fleet-launch-pump-{addr}").start()
        self.procs[addr] = proc
        return addr

    @staticmethod
    def _pump(proc) -> None:
        try:
            for _line in proc.stdout:
                pass
        except (OSError, ValueError):
            pass

    def stop(self, addr: str) -> None:
        proc = self.procs.pop(addr, None)
        if proc is None:
            return
        try:
            proc.terminate()
            proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            try:
                proc.kill()
            except OSError:
                pass

    def stop_all(self) -> None:
        for addr in list(self.procs):
            self.stop(addr)


_DEFAULT_COOLDOWNS = {"scale_out": 30.0, "scale_in": 60.0,
                      "recycle": 60.0, "rebalance": 10.0}


class FleetController:
    """The reconciling loop. Drive it with :meth:`tick` (tests, the
    console) or :meth:`start` (the ``tools/fleet_controller.py``
    daemon). One tick = read state, decide, act within the rails.

    ``launcher`` actuates scale_out/recycle spawns, ``weights_sink``
    (a ``dict[addr, weight]`` callable) actuates rebalance; either
    left None disables the actions that need it (journaled-skip free:
    an impossible action is simply never proposed).
    """

    def __init__(self, collector, engine, *,
                 launcher: ReplicaLauncher | None = None,
                 weights_sink=None,
                 min_replicas: int = 1, max_replicas: int = 4,
                 hysteresis: int = 2, calm_ticks: int = 5,
                 cooldown_s: dict | None = None,
                 budget_window_s: float = 300.0,
                 budget_max_actions: int = 10,
                 verify_s: float = 15.0,
                 drain_timeout_s: float = 30.0,
                 dry_run: bool = False,
                 history_max: int = 64,
                 http_timeout_s: float = 3.0):
        self.collector = collector
        self.engine = engine
        self.launcher = launcher
        self.weights_sink = weights_sink
        self.min_replicas = max(0, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.hysteresis = max(1, int(hysteresis))
        self.calm_ticks = max(1, int(calm_ticks))
        self.cooldown_s = dict(_DEFAULT_COOLDOWNS,
                               **(cooldown_s or {}))
        self.budget_window_s = float(budget_window_s)
        self.budget_max_actions = int(budget_max_actions)
        self.verify_s = float(verify_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.http_timeout_s = float(http_timeout_s)
        self._lock = threading.Lock()  # history/mode/budget vs status()
        self.mode = "dry_run" if dry_run else "active"
        self.history: list[dict] = []
        self.history_max = int(history_max)
        self._budget_monos: list[float] = []
        self._streak: dict[str, int] = {}
        self._recycle_key: str | None = None
        self._calm_streak = 0
        self._last_act_mono: dict[str, float] = {}
        self._seq = 0
        self._last_weights: dict[str, float] = {}
        # launched-but-not-yet-discovered replicas: counted into fleet
        # size so one overload doesn't double-launch inside the
        # collector's discovery latency
        self._expected: dict[str, float] = {}
        # drained replicas the collector hasn't noticed dying yet:
        # excluded from the live set so a victim is never re-drained
        # inside the staleness window
        self._drained: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        engine.subscribe(self._on_alert)
        self._transitions: list[dict] = []
        self._emit_gauges()

    # ------------------------------------------------------------ plumbing
    def _on_alert(self, rec: dict) -> None:
        """AlertEngine subscriber: remember recent transitions so a
        tick can cross-link actions to incident ids even when the
        firing list has already moved on."""
        with self._lock:
            self._transitions.append(rec)
            del self._transitions[:-64]

    def _emit_gauges(self, target: int | None = None) -> None:
        reg = get_registry()
        reg.gauge("controller_mode",
                  help="fleet-controller mode (0=active, 1=dry_run, "
                       "2=degraded budget_exhausted, "
                       "3=degraded store)").set(
            _MODE_VALUES.get(self.mode, 2.0))
        if target is not None:
            reg.gauge("fleet_target_replicas",
                      help="serving fleet size the controller is "
                           "reconciling toward").set(float(target))

    def _next_action_id(self, action: str) -> str:
        self._seq += 1
        return f"act-{action}-{int(time.time() * 1000)}-{self._seq}"

    def _record(self, rec: dict) -> None:
        with self._lock:
            self.history.append(rec)
            del self.history[:-self.history_max]

    def status(self) -> dict:
        """The console panel's view: mode, budget headroom, last
        actions (newest last)."""
        now = time.monotonic()
        with self._lock:
            spent = sum(1 for m in self._budget_monos
                        if now - m <= self.budget_window_s)
            return {"mode": self.mode,
                    "budget_spent": spent,
                    "budget_max": self.budget_max_actions,
                    "budget_window_s": self.budget_window_s,
                    "actions": list(self.history)}

    # --------------------------------------------------------------- rails
    def _budget_ok(self, now: float) -> bool:
        with self._lock:
            self._budget_monos = [m for m in self._budget_monos
                                  if now - m <= self.budget_window_s]
            return len(self._budget_monos) < self.budget_max_actions

    def _latch_degraded(self) -> None:
        if self.mode == "degraded (budget_exhausted)":
            return
        self.mode = "degraded (budget_exhausted)"
        self._emit_gauges()
        # LOUD: the latch is itself an incident — journaled, gauged,
        # printed
        events_lib.emit("action", "mode", mode=self.mode,
                        budget_max=self.budget_max_actions,
                        window_s=self.budget_window_s)
        print(f"[fleet-controller] action budget exhausted "
              f"({self.budget_max_actions} per "
              f"{self.budget_window_s:.0f}s): latched into "
              f"OBSERVE-ONLY degraded mode — reset_budget() to "
              f"re-arm", flush=True)

    def _update_store_hold(self) -> None:
        """The store-resilience contract: while the launcher-store
        health machine (store_plane, read through the collector) is
        degraded/down, the controller holds OBSERVE-ONLY — its view of
        the fleet rides discovery registries the dead store can no
        longer refresh, so actuating on it risks draining healthy
        replicas it merely can't see. Unlike the budget latch this
        hold clears ITSELF on recovery: the store coming back is the
        all-clear, no operator in the loop."""
        try:
            snap = self.collector.store_health()
        except Exception:
            return
        degraded = (isinstance(snap, dict) and snap.get("ops_total")
                    and snap.get("state") != "ok")
        if degraded and self.mode == "active":
            self.mode = "degraded (store)"
            self._emit_gauges()
            events_lib.emit("action", "mode", mode=self.mode,
                            store_state=snap.get("state"))
            print("[fleet-controller] launcher store "
                  f"{snap.get('state')}: holding OBSERVE-ONLY until "
                  "it recovers", flush=True)
        elif not degraded and self.mode == "degraded (store)":
            self.mode = "active"
            self._emit_gauges()
            events_lib.emit("action", "mode", mode=self.mode,
                            reason="store_recovered")
            print("[fleet-controller] launcher store recovered: "
                  "re-armed", flush=True)

    def reset_budget(self) -> None:
        """Operator re-arm after a ``budget_exhausted`` latch."""
        with self._lock:
            self._budget_monos.clear()
        if self.mode == "degraded (budget_exhausted)":
            self.mode = "active"
            self._emit_gauges()
            events_lib.emit("action", "mode", mode=self.mode,
                            reason="budget_reset")

    def _skip(self, action: str, reason: str, trigger: str,
              alert: dict | None, **detail) -> dict:
        aid = self._next_action_id(action)
        base = {"action": action, "id": aid, "trigger": trigger}
        if alert is not None and alert.get("id"):
            base["alert_id"] = alert["id"]
        events_lib.emit("action", "requested", **base, **detail)
        rec = {**base, "outcome": "skipped", "reason": reason, **detail}
        events_lib.emit("action", "skipped", **rec)
        get_registry().counter(
            "controller_actions_total",
            labels={"action": action, "outcome": "skipped"},
            help="fleet-controller actions by terminal outcome").inc()
        self._record(rec)
        return rec

    # ------------------------------------------------------------ execute
    def _execute(self, action: str, trigger: str, alert: dict | None,
                 fn, **detail) -> dict:
        """Run one decided action through the journaled lifecycle.
        ``fn()`` returns (outcome, detail_updates) with outcome in the
        action's declared set; any exception → ``failed``."""
        now = time.monotonic()
        aid = self._next_action_id(action)
        base = {"action": action, "id": aid, "trigger": trigger}
        if alert is not None and alert.get("id"):
            base["alert_id"] = alert["id"]
            base["alert_host"] = alert.get("host")
        events_lib.emit("action", "requested", **base, **detail)
        if self.mode == "dry_run":
            rec = {**base, "outcome": "skipped", "reason": "dry_run",
                   **detail}
            events_lib.emit("action", "skipped", **rec)
            get_registry().counter(
                "controller_actions_total",
                labels={"action": action, "outcome": "skipped"},
                help="fleet-controller actions by terminal "
                     "outcome").inc()
            with self._lock:
                # dry-run still honors the cooldown: one journaled
                # intent per window, not one per tick
                self._last_act_mono[action] = now
            self._record(rec)
            return rec
        events_lib.emit("action", "acting", **base)
        outcome, extra = "failed", {}
        try:
            fregistry.maybe_fire("controller.act")
            outcome, extra = fn()
        except Exception as e:  # noqa: BLE001 — every act failure is data
            outcome, extra = "failed", {
                "error": f"{type(e).__name__}: {e}"}
        # literal-unpack merge: an actuator's extra may repeat a key
        # the decision detail already carries (addr on drains) — the
        # actuator's value wins
        rec = {**base, "outcome": outcome,
               "after_s": round(time.monotonic() - now, 3),
               **detail, **extra}
        events_lib.emit("action", outcome, **rec)
        get_registry().counter(
            "controller_actions_total",
            labels={"action": action, "outcome": outcome},
            help="fleet-controller actions by terminal outcome").inc()
        with self._lock:
            self._budget_monos.append(now)
            self._last_act_mono[action] = now
        self._record(rec)
        return rec

    # ----------------------------------------------------------- actuators
    def _http_post(self, addr: str, path: str) -> int:
        req = urllib.request.Request(f"http://{addr}{path}", data=b"{}",
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(
                req, timeout=self.http_timeout_s) as r:
            return r.status

    def _healthz_status(self, addr: str) -> int | None:
        try:
            with urllib.request.urlopen(
                    f"http://{addr}/healthz",
                    timeout=self.http_timeout_s) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code
        except OSError:
            return None

    def _do_scale_out(self):
        addr = self.launcher.launch()
        if addr is None:
            return "failed", {"error": "launcher returned no address"}
        deadline = time.monotonic() + self.verify_s
        while time.monotonic() < deadline:
            if self._healthz_status(addr) is not None:
                with self._lock:
                    self._expected[addr] = time.monotonic() + 60.0
                return "effective", {"addr": addr}
            time.sleep(0.1)
        # launched but never answered: reverse it, loudly
        self.launcher.stop(addr)
        return "rolled_back", {"addr": addr,
                               "error": "replica never answered "
                                        "/healthz inside verify_s"}

    def _do_drain(self, addr: str):
        try:
            self._http_post(addr, "/admin/drain")
        except urllib.error.HTTPError:
            pass  # drain answered non-2xx: poll below decides
        except OSError:
            return "failed", {"addr": addr,
                              "error": "drain endpoint unreachable"}
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            if self._healthz_status(addr) is None:
                with self._lock:
                    self._drained[addr] = time.monotonic() + 60.0
                return "effective", {"addr": addr}
            time.sleep(0.1)
        return "failed", {"addr": addr,
                          "error": "replica still answering after "
                                   "drain_timeout_s"}

    def _do_recycle(self, addr: str):
        outcome, extra = self._do_drain(addr)
        if outcome != "effective":
            return outcome, extra
        if self.launcher is None:
            return "effective", dict(extra, replacement=None)
        out2, extra2 = self._do_scale_out()
        if out2 != "effective":
            return "failed", dict(extra, error="drained but "
                                  "replacement launch "
                                  f"{out2}: {extra2.get('error')}")
        return "effective", dict(extra,
                                 replacement=extra2.get("addr"))

    def _do_rebalance(self, weights: dict):
        self.weights_sink(dict(weights))
        self._last_weights = dict(weights)
        return "effective", {"weights": {a: round(w, 3)
                                         for a, w in weights.items()}}

    # ------------------------------------------------------------ policies
    @staticmethod
    def _weights_from(rows: list[dict]) -> dict[str, float]:
        """Dispatch weights from load: inverse queue depth, shedding
        replicas quartered, normalized so the best replica is 1.0."""
        raw = {}
        for r in rows:
            q = r.get("queue_depth")
            w = 1.0 / (1.0 + (float(q) if q is not None else 0.0))
            if r.get("admission") == "shedding":
                w *= 0.25
            raw[r["addr"]] = w
        top = max(raw.values(), default=0.0)
        if top <= 0.0:
            return {}
        return {a: w / top for a, w in raw.items()}

    def _weights_changed(self, weights: dict) -> bool:
        if not weights:
            return False
        for addr, w in weights.items():
            if abs(w - self._last_weights.get(addr, 1.0)) > 0.15:
                return True
        return False

    def _cooled(self, action: str, now: float) -> bool:
        last = self._last_act_mono.get(action)
        return (last is None
                or now - last >= self.cooldown_s.get(action, 0.0))

    def _rail_checked(self, action: str, trigger: str,
                      alert: dict | None, now: float,
                      fn, **detail) -> dict | None:
        """Common rails for one decided action: cooldown (silent
        suppress), budget latch + degraded mode (journaled skip),
        then execute. Returns the terminal record, or None when the
        cooldown suppressed the act."""
        if not self._cooled(action, now):
            return None
        if self.mode == "degraded (store)":
            # observe-only while the control plane is blind: the
            # decision is journaled (requested → skipped) so the
            # timeline shows what the controller WOULD have done
            rec = self._skip(action, "store_degraded", trigger,
                             alert, **detail)
            with self._lock:
                self._last_act_mono[action] = now
            return rec
        if self.mode == "degraded (budget_exhausted)" \
                or not self._budget_ok(now):
            if self.mode != "dry_run":
                self._latch_degraded()
            rec = self._skip(action, "budget_exhausted", trigger,
                             alert, **detail)
            with self._lock:
                self._last_act_mono[action] = now
            return rec
        return self._execute(action, trigger, alert, fn, **detail)

    # ----------------------------------------------------------------- tick
    def tick(self) -> list[dict]:
        """One reconcile pass. Returns the terminal action records it
        produced (empty on a quiet tick)."""
        now = time.monotonic()
        self._update_store_hold()
        rows = self.collector.serving_rows()
        with self._lock:
            self._drained = {a: d for a, d in self._drained.items()
                             if d > now}
            drained = set(self._drained)
        live = [r for r in rows
                if r["state"] == "ok" and r["addr"] not in drained]
        live_addrs = {r["addr"] for r in live}
        with self._lock:
            self._expected = {
                a: d for a, d in self._expected.items()
                if a not in live_addrs and d > now}
            pending = len(self._expected)
        fleet = len(live) + pending
        firing = self.engine.firing()
        by_rule: dict[str, dict] = {}
        for a in firing:
            by_rule.setdefault(a["rule"], a)
        out: list[dict] = []

        # ---- scale OUT: sustained overload triggers
        trig = next((t for t in ACTIONS["scale_out"].triggers
                     if t in by_rule), None)
        self._streak["scale_out"] = (
            self._streak.get("scale_out", 0) + 1 if trig else 0)
        if trig:
            self._calm_streak = 0
        else:
            self._calm_streak += 1
        if (trig and self.launcher is not None
                and self._streak["scale_out"] >= self.hysteresis):
            if fleet >= self.max_replicas:
                pass  # bounded: nothing to propose
            else:
                rec = self._rail_checked(
                    "scale_out", trig, by_rule[trig], now,
                    self._do_scale_out, fleet=fleet,
                    target=min(self.max_replicas, fleet + 1))
                if rec is not None:
                    out.append(rec)

        # ---- recycle: a diagnosed-sick serving host (drain +
        # replace, so the fleet floor holds; with no launcher the
        # drain alone must not take the fleet under min_replicas)
        sick = next(
            (a for a in firing
             if a["rule"] in ACTIONS["recycle"].triggers
             and any(r["host"] == a["host"] for r in live)), None)
        key = f"recycle:{sick['host']}" if sick else None
        self._streak["recycle"] = (
            self._streak.get("recycle", 0) + 1
            if sick and key == self._recycle_key
            else (1 if sick else 0))
        self._recycle_key = key
        if (sick and self._streak["recycle"] >= self.hysteresis
                and (self.launcher is not None
                     or fleet > self.min_replicas)):
            row = next(r for r in live if r["host"] == sick["host"])
            rec = self._rail_checked(
                "recycle", sick["rule"], sick, now,
                lambda: self._do_recycle(row["addr"]),
                addr=row["addr"], host=sick["host"])
            if rec is not None:
                out.append(rec)

        # ---- scale IN: calm fleet above the floor
        if (self._calm_streak >= self.calm_ticks
                and len(live) > self.min_replicas and pending == 0):
            victim = min(
                live, key=lambda r: (
                    (r.get("queue_depth")
                     if r.get("queue_depth") is not None else 0),
                    r.get("shed_per_s") or 0.0, r["addr"]))
            rec = self._rail_checked(
                "scale_in", "calm", None, now,
                lambda: self._do_drain(victim["addr"]),
                addr=victim["addr"], host=victim["host"],
                fleet=fleet, target=max(self.min_replicas, fleet - 1))
            if rec is not None:
                out.append(rec)

        # ---- rebalance: continuous weights policy
        if self.weights_sink is not None and len(live) >= 2:
            weights = self._weights_from(live)
            if self._weights_changed(weights):
                rec = self._rail_checked(
                    "rebalance", "policy", None, now,
                    lambda: self._do_rebalance(weights))
                if rec is not None:
                    out.append(rec)

        self._emit_gauges(target=max(
            self.min_replicas, min(self.max_replicas, fleet)))
        return out

    # ------------------------------------------------------------ threading
    def start(self, tick_s: float = 2.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(tick_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — loop must live
                    print(f"[fleet-controller] tick error "
                          f"{type(e).__name__}: {e}", flush=True)

        self._thread = threading.Thread(
            target=_loop, daemon=True, name="fleet-controller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
