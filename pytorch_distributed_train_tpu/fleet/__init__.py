"""Fleet control plane: the closed loop over alerts and actuators.

The observability planes diagnose (collector, alert engine, SLO
budgets); the serving planes actuate (advertise/discover, drain,
rolling restart, admission shed). This package is the connective
tissue: a reconciling controller that reads the former and drives the
latter, under hard safety rails (docs/autoscaler.md).
"""

from pytorch_distributed_train_tpu.fleet.controller import (  # noqa: F401
    ACTIONS,
    OUTCOMES,
    ActionSpec,
    FleetController,
    SubprocessReplicaLauncher,
)
