"""Retry/backoff recovery policies (exponential backoff + jitter).

The absorb-in-place half of the fault story: a transient I/O error on a
checkpoint save or a record decode should cost milliseconds of backoff,
not a whole-gang restart (minutes of re-init + re-compile + restore —
exactly the goodput hole SURVEY §5.3 describes). Every retry is counted
(``retries_total{point=...}``) and printed — a policy that absorbs
faults silently would hide a dying disk until the job ran out of
attempts at 3 a.m.

``decode_with_retry`` adds the data-pipeline-specific last resort:
SPMD batches have static shapes, so a record that stays undecodable
after all attempts cannot simply be dropped — it is SUBSTITUTED with a
neighboring record, counted in ``records_skipped_total``, and reported
on stderr (the torch DataLoader convention of raising and killing the
epoch trades one bad JPEG for the whole job; we trade it for one
duplicated sample).
"""

from __future__ import annotations

import dataclasses
import random
import sys
import time


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5  # +[0, jitter) fraction of the delay, decorrelates
    retry_on: tuple = (OSError,)


_DEFAULT = RetryPolicy()


def default_policy() -> RetryPolicy:
    return _DEFAULT


def set_default_policy(policy: RetryPolicy) -> None:
    """Install the process default (Trainer wires it from
    ``TrainConfig.faults``); call sites that pass no policy get it."""
    global _DEFAULT
    _DEFAULT = policy


def _counter(point: str):
    from pytorch_distributed_train_tpu.obs.registry import get_registry

    return get_registry().counter(
        "retries_total", labels={"point": point or "unlabeled"},
        help="operations retried after a transient fault, by fault point")


def retry_call(fn, *, policy: RetryPolicy | None = None, point: str = ""):
    """Call ``fn()``; on a retryable exception back off and try again,
    up to ``policy.max_attempts`` total attempts. The LAST failure
    propagates — retry exhaustion is the caller's fault to escalate, not
    this helper's to swallow."""
    policy = policy or _DEFAULT
    delay = policy.base_delay_s
    attempt = 1
    while True:
        try:
            return fn()
        except policy.retry_on as e:
            if attempt >= policy.max_attempts:
                raise
            _counter(point).inc()
            print(f"[retry] {point or 'op'} attempt {attempt}/"
                  f"{policy.max_attempts} failed ({type(e).__name__}: {e}); "
                  f"retrying in {delay:.3f}s", file=sys.stderr, flush=True)
            time.sleep(delay * (1.0 + policy.jitter * random.random()))
            delay = min(delay * 2.0, policy.max_delay_s)
            attempt += 1


def decode_with_retry(load, index: int, n_records: int, *,
                      policy: RetryPolicy | None = None,
                      max_substitutes: int = 2):
    """Decode record ``index`` via ``load(i)`` with retry; on exhaustion
    substitute up to ``max_substitutes`` neighboring records (static
    SPMD batch shapes forbid dropping a row). Never silent: the skip is
    counted and printed. Raises the final error only when the
    substitutes fail too."""
    policy = policy or _DEFAULT
    try:
        return retry_call(lambda: load(int(index)), policy=policy,
                          point="data.decode")
    except policy.retry_on as e:
        last = e
    from pytorch_distributed_train_tpu.obs.registry import get_registry

    get_registry().counter(
        "records_skipped_total",
        help="records replaced by a substitute after decode retries "
             "were exhausted").inc()
    for k in range(1, max_substitutes + 1):
        sub = (int(index) + k) % max(n_records, 1)
        print(f"[decode] record {index} undecodable after "
              f"{policy.max_attempts} attempts ({type(last).__name__}: "
              f"{last}); substituting record {sub}",
              file=sys.stderr, flush=True)
        try:
            return retry_call(lambda: load(sub), policy=policy,
                              point="data.decode")
        except policy.retry_on as e:
            last = e
    raise last
