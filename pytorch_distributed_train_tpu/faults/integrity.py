"""Checkpoint integrity manifests: prove a step is restorable BEFORE
restoring it.

Orbax's atomic-rename commit protects against a crash DURING a save
(the half-written step stays under a ``.orbax-checkpoint-tmp-*`` name),
but nothing protects against corruption AFTER commit — a truncated
array file from a dying disk, an rsync that copied half a step, an
operator's stray ``rm``. A resume that restores such a step either
crashes the gang (best case) or silently trains on garbage. So after
each commit we write a per-step manifest beside the step tree:

    <ckpt_dir>/manifests/step_<N>.json
      {"step": N, "config_sha256": ..., "files": {relpath:
        {"size": bytes, "sha256": hex-or-null}}, "manifest_sha256": ...}

``files`` inventories every file under the committed step directory
with its size, plus a content hash for files up to ``HASH_MAX_BYTES``
(sizes catch truncation for free; hashing terabyte-scale shards on
every save would tax exactly the I/O path checkpointing competes for).
``manifest_sha256`` self-seals the manifest body. Verification on
restore checks presence + size + hash; ``latest_good_step`` walks steps
newest-first and falls back past any step that fails, logging what it
skipped and counting it in ``ckpt_integrity_failures_total``.

Manifests live OUTSIDE the step directory so Orbax's layout stays
untouched — and so truncating/deleting files inside a step cannot also
delete the evidence needed to detect it. Pre-manifest checkpoints
(written before this layer existed) verify as "unknown" and are
trusted, preserving resume compatibility.
"""

from __future__ import annotations

import hashlib
import json
import os

MANIFEST_DIRNAME = "manifests"
# Per-file content-hash cap: sizes are always recorded; content hashes
# only for files at or under this many bytes (TensorStore shards of a
# 7B run are GBs each — hashing them doubles save I/O for little
# marginal protection over the size check).
HASH_MAX_BYTES = 256 * 1024 * 1024


def manifest_path(root: str, step: int) -> str:
    return os.path.join(root, MANIFEST_DIRNAME, f"step_{int(step)}.json")


def has_manifest(root: str, step: int) -> bool:
    return os.path.exists(manifest_path(root, step))


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, str(int(step)))


def step_committed(root: str, step: int) -> bool:
    """Whether Orbax finished committing this step: the FINAL-named
    directory exists (an in-flight async save lives under a
    ``.orbax-checkpoint-tmp-*`` name until its rename)."""
    return os.path.isdir(step_dir(root, step))


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _inventory(sdir: str) -> dict[str, dict]:
    files: dict[str, dict] = {}
    for dirpath, _, names in os.walk(sdir):
        for name in names:
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, sdir)
            size = os.path.getsize(full)
            files[rel] = {
                "size": int(size),
                "sha256": (_sha256_file(full)
                           if size <= HASH_MAX_BYTES else None),
            }
    return files


def _seal(body: dict) -> str:
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def write_manifest(root: str, step: int, config_json: str = "") -> str:
    """Inventory the committed step and write its manifest atomically
    (tmp + rename: a manifest must never itself be a partial file)."""
    sdir = step_dir(root, step)
    body = {
        "step": int(step),
        "config_sha256": hashlib.sha256(
            (config_json or "").encode()).hexdigest(),
        "files": _inventory(sdir),
    }
    body["manifest_sha256"] = _seal(
        {k: v for k, v in body.items() if k != "manifest_sha256"})
    path = manifest_path(root, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(body, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def verify_step(root: str, step: int) -> tuple[bool | None, str]:
    """(ok, reason). ok=None means "no manifest" — a pre-manifest
    checkpoint the caller should trust for back-compat."""
    path = manifest_path(root, step)
    if not os.path.exists(path):
        return None, "no manifest"
    try:
        with open(path) as f:
            body = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest: {e}"
    sealed = body.get("manifest_sha256")
    if sealed != _seal(
            {k: v for k, v in body.items() if k != "manifest_sha256"}):
        return False, "manifest seal mismatch (manifest itself corrupt)"
    sdir = step_dir(root, step)
    if not os.path.isdir(sdir):
        return False, "step directory missing"
    for rel, meta in body.get("files", {}).items():
        full = os.path.join(sdir, rel)
        if not os.path.exists(full):
            return False, f"missing file {rel}"
        size = os.path.getsize(full)
        if size != meta["size"]:
            return False, (f"size mismatch {rel}: "
                           f"{size} != {meta['size']}")
        if meta.get("sha256") and size <= HASH_MAX_BYTES:
            if _sha256_file(full) != meta["sha256"]:
                return False, f"content hash mismatch {rel}"
    return True, "ok"


def prune_manifests(root: str, live_steps) -> None:
    """Drop manifests whose step Orbax already garbage-collected
    (max_to_keep) — a stale manifest is harmless but misleading."""
    mdir = os.path.join(root, MANIFEST_DIRNAME)
    if not os.path.isdir(mdir):
        return
    live = {int(s) for s in live_steps}
    for name in os.listdir(mdir):
        if not (name.startswith("step_") and name.endswith(".json")):
            continue
        try:
            step = int(name[len("step_"):-len(".json")])
        except ValueError:
            continue
        if step not in live:
            try:
                os.remove(os.path.join(mdir, name))
            except OSError:
                pass  # best-effort housekeeping
