"""Fault-injection chaos layer + recovery policies (ISSUE 2 tentpole).

Three pillars, wired through trainer / checkpoint / data / elastic /
serving (docs/fault_tolerance.md has the catalog and recovery matrix):

- ``registry``   — named fault points (``ckpt.save_io``, ``data.decode``,
                   ``step.crash``, ``step.straggle``, ``preempt.sigterm``,
                   ``serve.handler``) driven by a declarative schedule
                   (``TrainConfig.faults.inject`` / ``PDTT_FAULTS`` env),
                   counted in ``faults_injected_total{point=...}``.
- ``retry``      — exponential-backoff + jitter retry policies
                   (``retries_total``), plus the decode
                   substitute-and-count last resort
                   (``records_skipped_total``).
- ``preemption`` — SIGTERM → checkpoint-and-clean-exit, composing with
                   the watchdog's diagnostics dump in either install
                   order.
- ``integrity``  — per-step checkpoint manifests; ``latest_good_step``
                   falls back past corrupt/partial steps
                   (``ckpt_integrity_failures_total``).

Plain host-side Python: no jax at module scope, so data-loader worker
processes and serving tools can traverse fault points freely.
"""

from pytorch_distributed_train_tpu.faults.registry import (  # noqa: F401
    ENV_VAR,
    FaultSchedule,
    FaultSpec,
    InjectedFault,
    POINTS,
    configure,
    get_schedule,
    maybe_fire,
    parse_spec,
    set_step,
)
from pytorch_distributed_train_tpu.faults.retry import (  # noqa: F401
    RetryPolicy,
    decode_with_retry,
    default_policy,
    retry_call,
    set_default_policy,
)
