"""Graceful SIGTERM preemption: checkpoint-and-clean-exit instead of
crash-and-restart.

The scheduler's preemption signal (GKE sends SIGTERM with a grace
window before SIGKILL) used to be a hard stop: the watchdog's dump
handler wrote diagnostics and ``sys.exit(143)``, losing every step
since the last cadence checkpoint and forcing the full
kill→respawn→re-init→re-compile→restore cycle on the next run. With
graceful preemption armed, SIGTERM only SETS A FLAG; the train loop
checks it at the next step boundary, forces a synchronized checkpoint
save, writes a ``preempted`` marker into the summary record, and
returns cleanly — the restarted job resumes with at most one step of
lost work instead of ``save_every_steps``.

Composition with the watchdog's dump handler (utils/watchdog.py) works
in EITHER install order: both handlers chain to whatever was installed
before them, and the watchdog's terminal ``sys.exit(143)`` is
suppressed while a preemption handler is armed (``armed()`` below is
its check) — diagnostics still dump, but the train loop owns the exit.

Multi-host note: the forced save is a collective (every host's Orbax
writer participates), which is safe because preemption signals the
whole job — a single-host SIGTERM with peers still training would wait
in the save barrier until the heartbeat or the scheduler escalates.
"""

from __future__ import annotations

import signal
import threading
import time

_ARMED = 0  # count of installed handlers (module-level so the watchdog
_LOCK = threading.Lock()  # can ask "is anyone graceful?" without a ref


def armed() -> bool:
    """True when a PreemptionHandler is installed in this process —
    read by the watchdog's SIGTERM dump handler to leave process exit
    to the train loop."""
    return _ARMED > 0


class PreemptionHandler:
    """Installs a chaining SIGTERM handler that records the request and
    returns (never exits). Check ``requested`` at step boundaries."""

    def __init__(self):
        self._event = threading.Event()
        self._prev = None
        self._installed = False
        self.requested_at: float | None = None

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def install(self) -> None:
        if self._installed:
            return
        try:
            self._prev = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, self._handle)
        except ValueError:  # not the main thread (tests)
            return
        self._installed = True
        global _ARMED
        with _LOCK:
            _ARMED += 1

    def uninstall(self) -> None:
        """Restore the previous handler (tests; trainers run to exit)."""
        if not self._installed:
            return
        try:
            signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
        except ValueError:
            pass
        self._installed = False
        global _ARMED
        with _LOCK:
            _ARMED = max(0, _ARMED - 1)

    def _handle(self, signum, frame) -> None:
        first = not self._event.is_set()
        self._event.set()
        if first:
            self.requested_at = time.monotonic()
            print("[preempt] SIGTERM received — will checkpoint and exit "
                  "cleanly at the next step boundary", flush=True)
        prev = self._prev
        if callable(prev) and prev not in (signal.default_int_handler,):
            # Chain (e.g. the watchdog's diagnostics dump). The chained
            # handler sees armed()=True and must not exit.
            prev(signum, frame)
