"""Fault-injection registry: named fault points + a declarative schedule.

The PR-1 observability layer made every run legible; this layer makes
every FAILURE legible — and scriptable. Call sites that can fail in
production declare a named fault point (the catalog below) and traverse
it on the hot path; a schedule parsed from ``TrainConfig.faults.inject``
(or the ``PDTT_FAULTS`` env var, for subprocess workers and the serving
tool) decides which traversals actually fire. This replaces the single
hard-kill hook (``obs.fault_inject_at_step``, now routed through here as
``step.crash@step=N``) with multi-fault scenarios a test or soak run can
compose: "two transient checkpoint I/O errors at step 3, then a SIGTERM
preemption at step 5".

Schedule grammar (one spec per entry)::

    <point>@<key>=<value>[:<key>=<value>...]

    keys: step  — fire once the trainer's step counter reaches this value
          call  — fire on the Nth traversal of the point (1-based; for
                  points with no step context, e.g. serve.handler)
          p     — per-traversal probability (seeded; chaos soak)
          count — how many times to fire (default 1)
          gen   — restart generation to fire in (default 0: first
                  generation only, so a supervised job faults once and
                  must recover; -1 = every generation)
          rc    — exit code for step.crash (default 41)
          delay — straggle sleep seconds for step.straggle (default 2.0)
          for   — OUTAGE WINDOW seconds: once the spec's trigger first
                  matches, the point fires on EVERY traversal for this
                  many wall-seconds (monotonic), then exhausts; count=
                  is ignored. ``store.get@call=1:for=6`` is a 6-second
                  store-read blackout — the store-resilience drills'
                  primitive (docs/fault_tolerance.md)

What firing MEANS is a property of the point, not the spec: I/O-shaped
points raise ``InjectedFault`` (an OSError, so the retry policies treat
it exactly like a real transient error), ``step.crash`` hard-exits,
``step.straggle`` sleeps, ``preempt.sigterm`` delivers a real SIGTERM to
this process. Every fire increments ``faults_injected_total{point=...}``
in the obs registry, so a soak run's report can prove the faults
actually happened.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time

# point name -> action performed when a matching spec fires
POINTS: dict[str, str] = {
    "ckpt.save_io": "raise",     # checkpoint save I/O (checkpoint.py)
    "ckpt.persist_io": "raise",  # background persist I/O (ckpt/manager.py
                                 # persister thread — the async plane's
                                 # Orbax write, distinct from save_io)
    "ckpt.peer_fetch": "raise",  # peer snapshot fetch over the KV store
                                 # (ckpt/peer.py; exhausted retries fall
                                 # back to persistent storage)
    "data.decode": "raise",      # record decode (data/pipeline, grain)
    "serve.handler": "raise",    # HTTP request handler (tools/serve_http)
    # Serving reliability plane drill points (serving_plane/;
    # docs/serving_reliability.md). Same stance as the sentinel "flag"
    # points: what a serving fault MEANS is a property of the service
    # loop, not this registry.
    "serve.deadline": "flag",    # scheduler force-expires the oldest
                                 # in-flight request's deadline (504 +
                                 # slot reclaim, deterministically)
    "serve.slot_leak": "flag",   # abandon path SKIPS its cancel/release
                                 # — recreates the pre-fix slot leak the
                                 # leak sweep must then catch
    "serve.slow_decode": "sleep",  # delay injected into the batcher's
                                   # decode quantum (tail-latency spike
                                   # the TTFT/inter-token detector sees)
    "step.crash": "exit",        # hard process kill between steps
    "step.straggle": "sleep",    # transient slow step (straggler)
    "elastic.shrink": "exit",    # permanent host loss (rc 45): under a
                                 # min_nnodes launcher whose node has no
                                 # restart budget, the gang re-rendezvouses
                                 # DEGRADED and resumes resharded —
                                 # docs/elastic.md shrink drill
    "preempt.sigterm": "sigterm",  # scheduler preemption drill
    # Sentinel drill points (sentinel/; docs/sentinel.md). "flag" points
    # only RETURN True — the call site performs the corruption, because
    # what "a numeric fault" means is a property of the trainer (poison
    # the next batch / inflate the observed loss), not of this registry.
    "step.nan": "flag",          # trainer poisons the next batch to NaN
    "step.loss_spike": "flag",   # trainer inflates the OBSERVED loss
    "step.grad_spike": "flag",   # trainer inflates the OBSERVED grad/
                                 # update telemetry (post-backward,
                                 # pre-clip observation; params and loss
                                 # untouched) — the model-health
                                 # early-warning drill (obs/model_health)
    "host.hang": "hang",         # wedge this host forever (collective
                                 # deadlock seen from outside)
    "controller.act": "raise",   # fleet-controller actuation start
                                 # (fleet/controller.py): the act fails
                                 # before touching the fleet, so the
                                 # failed/rolled_back journaling and the
                                 # action budget are drillable
    # Store-resilience drill points (store_plane.py ResilientStore;
    # docs/fault_tolerance.md degraded-mode matrix). Traversed INSIDE
    # the bounded op path, so an injected outage exercises exactly the
    # deadline/retry/LKG machinery a real one would. Combine with for=
    # for blackout windows, and set PDTT_FAULTS on a single host for a
    # per-host partition.
    "store.get": "raise",        # launcher-store read (get/wait/numkeys)
    "store.set": "raise",        # launcher-store write (set/delete)
    "store.add": "raise",        # launcher-store counter add
    "store.latency": "sleep",    # injected latency before every store
                                 # op (latency storm: ops hit their
                                 # ResilientStore deadline instead of
                                 # stalling the caller)
    # Online post-training plane drill points (online/;
    # docs/online_training.md). The loop's three failure surfaces:
    # publishing trainer weights, swapping them onto a replica, and
    # harvesting rollouts — each must degrade (keep the old version /
    # retry the fetch), never corrupt state or fail live requests.
    "weights.publish": "raise",  # trainer-side weight publish to the
                                 # KV store (online/publisher.py): the
                                 # step loop's cadence skips a beat,
                                 # replicas keep serving and lag grows
    "weights.swap": "raise",     # replica-side swap request (serve_http
                                 # /admin/weights): 503 to the caller,
                                 # the replica keeps its current version
    "rollout.fetch": "raise",    # rollout harvest HTTP fetch
                                 # (online/rollouts.py; retry_call at
                                 # the driver wraps it — exhausted
                                 # retries skip the batch, never feed a
                                 # partial one to a train step)
}


class InjectedFault(OSError):
    """An injected transient fault. Subclasses OSError so retry policies
    treat it exactly like the real I/O error it stands in for."""


@dataclasses.dataclass
class FaultSpec:
    point: str
    step: int | None = None
    at_call: int | None = None
    p: float = 0.0
    count: int = 1
    gen: int = 0
    rc: int = 41
    delay_s: float = 2.0
    for_s: float = 0.0
    # mutable bookkeeping
    fired: int = 0
    calls: int = 0
    window_start: float | None = None  # monotonic; for= window open mark
    window_done: bool = False

    def spec_str(self) -> str:
        parts = []
        if self.step is not None:
            parts.append(f"step={self.step}")
        if self.at_call is not None:
            parts.append(f"call={self.at_call}")
        if self.p:
            parts.append(f"p={self.p}")
        if self.for_s > 0.0:
            parts.append(f"for={self.for_s}")
        else:
            parts.append(f"count={self.count}")
        return f"{self.point}@" + ":".join(parts)


_INT_KEYS = {"step", "call", "count", "gen", "rc"}
_FLOAT_KEYS = {"p", "delay", "for"}


def parse_spec(spec: str) -> FaultSpec:
    """``point@key=val[:key=val...]`` → FaultSpec (ValueError on typos:
    an injection schedule that silently does nothing is itself a silent
    fault)."""
    text = spec.strip()
    if "@" not in text:
        raise ValueError(
            f"fault spec {spec!r}: expected '<point>@key=val[:key=val...]' "
            f"(points: {sorted(POINTS)})")
    point, _, rest = text.partition("@")
    point = point.strip()
    if point not in POINTS:
        raise ValueError(
            f"fault spec {spec!r}: unknown point {point!r} "
            f"(points: {sorted(POINTS)})")
    out = FaultSpec(point=point)
    if point == "elastic.shrink":
        # Distinct default rc: a supervising drill (tools/chaos_soak.py
        # --shrink) tells "host permanently lost" apart from step.crash's
        # generic 41. rc= in the spec still overrides.
        out.rc = 45
    for part in filter(None, (p.strip() for p in rest.split(":"))):
        if "=" not in part:
            raise ValueError(f"fault spec {spec!r}: bad clause {part!r}")
        k, _, v = part.partition("=")
        k = k.strip()
        try:
            if k in _INT_KEYS:
                val = int(v)
            elif k in _FLOAT_KEYS:
                val = float(v)
            else:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"fault spec {spec!r}: bad clause {part!r} "
                f"(keys: {sorted(_INT_KEYS | _FLOAT_KEYS)})") from None
        if k == "step":
            out.step = val
        elif k == "call":
            out.at_call = val
        elif k == "p":
            out.p = val
        elif k == "count":
            out.count = val
        elif k == "gen":
            out.gen = val
        elif k == "rc":
            out.rc = val
        elif k == "delay":
            out.delay_s = val
        elif k == "for":
            out.for_s = val
    if out.step is None and out.at_call is None and out.p <= 0.0:
        raise ValueError(
            f"fault spec {spec!r}: needs at least one trigger "
            "(step=, call=, or p=)")
    return out


class FaultSchedule:
    """Parsed injection schedule + the traversal-time matching logic.

    Thread model: fault points are traversed from the step loop, data
    producer/decode threads, and HTTP handler threads; matching mutates
    per-spec counters under one lock (traversals are rare relative to
    work done between them, and correctness of count= demands atomicity).
    """

    def __init__(self, specs: tuple[str, ...] = (), seed: int = 0):
        self.specs = [parse_spec(s) for s in specs]
        self._lock = threading.Lock()
        self._step: int | None = None
        import numpy as np

        self._rng = np.random.default_rng(seed)

    # ------------------------------------------------------------ trainer
    def set_step(self, step: int) -> None:
        self._step = step

    # ------------------------------------------------------------ matching
    def _generation(self) -> str:
        return os.environ.get("RESTART_GENERATION", "0")

    def check(self, point: str, step: int | None = None) -> FaultSpec | None:
        """One traversal of ``point``: returns the spec that fires, or
        None. Firing decrements the spec's remaining count. ``step``
        overrides the trainer-set counter for this traversal — call
        sites that know their step (checkpoint save in a tool, a test
        driving CheckpointManager directly) match step= specs without a
        Trainer loop running set_step."""
        if point not in POINTS:
            raise KeyError(f"undeclared fault point {point!r} "
                           f"(catalog: {sorted(POINTS)})")
        gen = self._generation()
        cur_step = step if step is not None else self._step
        with self._lock:
            for spec in self.specs:
                if spec.point != point:
                    continue
                if spec.gen >= 0 and gen != str(spec.gen):
                    continue
                spec.calls += 1
                if spec.for_s > 0.0:
                    # Outage-window semantics: fire on EVERY traversal
                    # from first trigger match until for_s monotonic
                    # seconds elapse, then exhaust (count= is ignored).
                    if spec.window_done:
                        continue
                    if spec.window_start is not None:
                        if (time.monotonic() - spec.window_start
                                < spec.for_s):
                            spec.fired += 1
                            return spec
                        spec.window_done = True
                        continue
                elif spec.fired >= spec.count:
                    continue
                if spec.step is not None and (
                        cur_step is None or cur_step < spec.step):
                    continue
                if spec.at_call is not None and spec.calls < spec.at_call:
                    continue
                if spec.p > 0.0 and not (self._rng.random() < spec.p):
                    continue
                if spec.for_s > 0.0:
                    spec.window_start = time.monotonic()
                spec.fired += 1
                return spec
        return None

    # -------------------------------------------------------------- firing
    def maybe_fire(self, point: str, step: int | None = None) -> bool:
        """Traverse ``point``; perform the point's action if a spec fires.

        Returns False when nothing fired. ``raise``-kind points raise
        InjectedFault; exit/sleep/sigterm perform their side effect and
        return True."""
        spec = self.check(point, step=step)
        if spec is None:
            return False
        from pytorch_distributed_train_tpu.obs import events as events_lib
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        get_registry().counter(
            "faults_injected_total", labels={"point": point},
            help="deliberately injected faults by fault point").inc()
        action = POINTS[point]
        # Journal BEFORE the action runs: step.crash hard-exits and
        # host.hang never returns — the flushed-per-line journal is the
        # only record that survives either.
        events_lib.emit("fault", point, step=step, action=action,
                        spec=spec.spec_str())
        at = f" at step {step}" if step is not None else ""
        if action == "exit":
            print(f"[fault-inject] killing process{at} ({point})",
                  flush=True)
            os._exit(spec.rc)
        if action == "sleep":
            print(f"[fault-inject] straggling {spec.delay_s}s{at} "
                  f"({point})", flush=True)
            time.sleep(spec.delay_s)
            return True
        if action == "sigterm":
            print(f"[fault-inject] SIGTERM to self{at} ({point})",
                  flush=True)
            os.kill(os.getpid(), signal.SIGTERM)
            return True
        if action == "flag":
            # The corruption itself is the call site's job (trainer:
            # batch poisoning for step.nan, observed-loss inflation for
            # step.loss_spike) — firing only reports the schedule match.
            print(f"[fault-inject] flagging {point}{at}", flush=True)
            return True
        if action == "hang":
            # Wedge THIS host forever inside an open span, so the
            # cross-host liveness monitor (sentinel/liveness.py) can
            # name the phase it is "stuck" in: the local heartbeat
            # never beats again, the store heartbeat goes stale, and
            # only an external abort ends this — exactly what a wedged
            # collective looks like from outside.
            print(f"[fault-inject] wedging host forever{at} ({point})",
                  flush=True)
            from pytorch_distributed_train_tpu.obs.spans import span

            with span("fault.host_hang", step=step):
                while True:
                    time.sleep(60)
        raise InjectedFault(
            f"injected fault: {point}{at} ({spec.spec_str()})")


# ------------------------------------------------------------- process-global
_SCHEDULE: FaultSchedule | None = None
_LOCK = threading.Lock()

ENV_VAR = "PDTT_FAULTS"


def _env_specs() -> tuple[str, ...]:
    raw = os.environ.get(ENV_VAR, "")
    return tuple(s.strip() for s in raw.split(",") if s.strip())


def configure(specs: tuple[str, ...] = (), seed: int = 0,
              legacy_crash_step: int = 0) -> FaultSchedule:
    """Install the process-global schedule from config specs + the
    PDTT_FAULTS env var. ``legacy_crash_step`` routes the deprecated
    ``obs.fault_inject_at_step`` hook through the registry as
    ``step.crash@step=N`` (generation 0 only — the original contract)."""
    global _SCHEDULE
    all_specs = tuple(specs) + _env_specs()
    if legacy_crash_step:
        all_specs += (f"step.crash@step={int(legacy_crash_step)}",)
    sched = FaultSchedule(all_specs, seed=seed)
    with _LOCK:
        _SCHEDULE = sched
    return sched


def get_schedule() -> FaultSchedule:
    """The process-global schedule; lazily built from PDTT_FAULTS alone
    when nothing configured one (serving tools, data workers)."""
    global _SCHEDULE
    if _SCHEDULE is None:
        with _LOCK:
            if _SCHEDULE is None:
                _SCHEDULE = FaultSchedule(_env_specs())
    return _SCHEDULE


def maybe_fire(point: str, step: int | None = None) -> bool:
    return get_schedule().maybe_fire(point, step=step)


def set_step(step: int) -> None:
    get_schedule().set_step(step)


def _reset_for_tests() -> None:
    global _SCHEDULE
    with _LOCK:
        _SCHEDULE = None
