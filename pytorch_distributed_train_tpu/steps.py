"""The jitted train/eval step — the whole reference hot loop as ONE program.

The reference's step (SURVEY §3.3) is five runtime phases: autocast forward,
DDP-hooked backward with bucketed NCCL all-reduce (reducer.hpp:285),
GradScaler unscale+check, optimizer step, scheduler step. Here that entire
block is a single XLA executable: forward + loss + grad + compiler-placed
collectives + optax update, with overlap done by XLA's latency-hiding
scheduler instead of autograd hooks (SURVEY C7 — "obsolete by construction").

Sharding contract: the TrainState and batch arrive as jax.Arrays already laid
out per the partition rules; `jit(in_shardings=..., donate_argnums=0)` makes
the update in-place in HBM. One PartitionRules table shards params AND
optimizer state AND batch stats — optax state mirrors the param tree
structure, and the '$'-anchored suffix regexes match either path.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Re-exports: the preset + env helper live in config.py (jax-free —
# XLA_FLAGS must be set before any backend-registering import, which
# importing THIS module may already have done).
from pytorch_distributed_train_tpu.config import (  # noqa: F401
    LATENCY_HIDING_XLA_FLAGS,
    ensure_latency_hiding_flags,
)
from pytorch_distributed_train_tpu.train_state import TrainState


def dummy_inputs(loss: str, model_cfg, data_cfg) -> tuple:
    """Tiny dummy inputs for model.init / eval_shape, dispatched the same
    way ``model_inputs`` dispatches real batches (shared by Trainer init
    and distill.py's teacher loading)."""
    if loss == "softmax_xent":
        return (jnp.zeros(
            (2, model_cfg.image_size, model_cfg.image_size, 3),
            jnp.float32),)
    if loss == "mlm_xent":
        ids = jnp.zeros((2, data_cfg.seq_len), jnp.int32)
        return (ids, jnp.ones((2, data_cfg.seq_len), jnp.int32))
    if loss == "seq2seq_xent":
        return (jnp.zeros((2, data_cfg.seq_len), jnp.int32),
                jnp.zeros((2, data_cfg.tgt_seq_len or data_cfg.seq_len),
                          jnp.int32))
    return (jnp.zeros((2, data_cfg.seq_len), jnp.int32),)


def model_inputs(batch: dict) -> tuple:
    """Dispatch batch dict → model positional args (registry-wide convention:
    vision models take images NHWC; BERT takes (input_ids, attention_mask);
    causal LMs take input_ids)."""
    if "image" in batch:
        return (batch["image"],)
    if "decoder_input_ids" in batch:  # seq2seq (t5) — before the bert key
        return (batch["input_ids"], batch["decoder_input_ids"])
    if "attention_mask" in batch:
        return (batch["input_ids"], batch["attention_mask"])
    ids = batch["input_ids"]
    if ids.ndim == 3:
        # preference pairs (B, 2, S) — DPO; the model scores the pair as
        # one flattened (2B, S) forward (losses.make_dpo_loss un-flattens)
        ids = ids.reshape(-1, ids.shape[-1])
    return (ids,)


def apply_model(model, params, batch_stats, batch, *, train: bool, dropout_rng):
    """Returns (logits, new_batch_stats, aux_loss).

    aux_loss is the sum of everything the model ``sow``ed into the 'losses'
    collection (MoE load-balance/z-loss, ops/moe.py) — 0.0 for dense models.
    """
    variables: dict[str, Any] = {"params": params}
    # mutable must be False (not []) when there are no stats — flax returns a
    # (out, vars) tuple for ANY list, including an empty one.
    mutable: Any = False
    if train:
        mutable = ["losses"]
    if batch_stats:
        variables["batch_stats"] = batch_stats
        if train:
            mutable = ["batch_stats", "losses"]
    rngs = {"dropout": dropout_rng} if dropout_rng is not None else None
    kwargs = {}
    if "decoder_input_ids" in batch and "attention_mask" in batch:
        # seq2seq (t5): the encoder padding mask rides as a kwarg (the
        # positional slots are taken by the two id tensors).
        kwargs["attention_mask"] = batch["attention_mask"]
    if getattr(model, "fused_loss", False) and "loss_mask" in batch:
        # Fused-head models reduce CE inside the model (losses.
        # chunked_causal_ce), so the mask must travel in with the inputs.
        kwargs["loss_mask"] = batch["loss_mask"]
    out = model.apply(
        variables, *model_inputs(batch), train=train, rngs=rngs,
        mutable=mutable, **kwargs
    )
    if mutable:
        logits, updated = out
        aux = sum(
            (jnp.sum(leaf) for leaf in jax.tree_util.tree_leaves(
                updated.get("losses", {}))),
            start=jnp.float32(0.0),
        )
        return logits, updated.get("batch_stats"), aux
    return out, None, jnp.float32(0.0)


def _tree_finite(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    finite = jnp.bool_(True)
    for leaf in leaves:
        finite &= jnp.all(jnp.isfinite(leaf))
    return finite


def make_train_step(model, loss_fn: Callable, tx,
                    ema_decay: float = 0.0, swa_start: int = 0,
                    swa_every: int = 1, mixup=None,
                    device_augment=None,
                    module_grad_norms: bool = False,
                    param_transform: Callable | None = None,
                    teacher_fn: Callable | None = None,
                    numeric_guard: bool = False,
                    grad_accum_steps: int = 1,
                    fused_update=None,
                    reduce_grads: Callable | None = None,
                    reduce_grads_accum: Callable | None = None,
                    reduce_metrics: Callable | None = None,
                    model_health: bool = False) -> Callable:
    """Returns train_step(state, batch, rng) -> (state, metrics). Pure;
    closes over the optax transform (and the static EMA decay / mixup
    transform); jit-wrapped by the caller with explicit shardings.
    ``module_grad_norms`` adds per-top-level-module grad norms to the
    metrics (grad_norm/<module> keys) — the torch-recipe debugging habit
    of watching which block's gradients explode/vanish; computed in-graph,
    so it costs a few reductions, not a host transfer per param.
    ``model_health`` (obs/model_health.py) widens that to the full
    training-dynamics pass (ops/model_health.health_stats): per-module
    grad/param/update norms and update-to-param ratios plus tree-wide
    aggregates, all reduced in-graph. It only ADDS metrics entries — the
    update path is bitwise identical with the flag off.
    ``numeric_guard`` (sentinel/) generalizes the GradScaler skip-step to
    UNSCALED training: a non-finite grad or loss skips the optimizer
    update in-graph (params/opt-state unchanged, step still advances)
    and reports ``update_skipped`` in the metrics — one NaN batch costs
    one skipped step instead of permanently poisoned params. With
    dynamic loss scaling the scaler's own finite gate already does this;
    the guard then only widens the check to include the loss value.

    Compute-graph optimization layer (train.* knobs, docs/performance.md):

    ``grad_accum_steps > 1`` microbatches the step IN-GRAPH: a
    ``lax.scan`` over N equal microbatch slices of the (donated) global
    batch accumulates grads in the carry; loss/metrics are the mean of
    the per-microbatch means and the whole epilogue below — loss-scale
    unscale, finite gate, clip, optimizer — runs ONCE on the
    accumulated grads, so skip/rewind semantics and the LR schedule's
    step count are those of the single-shot step at the same global
    batch (optax.MultiSteps instead runs N host-driven micro-steps and
    gates each one). Dropout/augment keys fold the microbatch index on
    top of the per-step fold, so each microbatch draws independently
    and deterministically under resume.

    ``fused_update`` (ops/fused_update.py via optim.make_fused_update)
    replaces the clip → optax-chain → apply_updates → gate-select
    pipeline with the one-pass fused epilogue; semantics are pinned
    bit-for-bit to the chain by tests. Mutually exclusive with EMA/SWA
    (the fused path does not maintain the mirror).

    ``reduce_grads`` / ``reduce_grads_accum`` / ``reduce_metrics`` are
    the shard_map hooks of the overlapped-collectives path
    (``jit_overlap_train_step``): per-microbatch bucketed grad
    reduction inside the scan (DDP-reducer overlap), whole-tree
    reduction of the accumulated grads (the monolithic baseline arm),
    and cross-shard averaging of loss/metrics/batch-stats. All None
    under plain GSPMD jit, where the partitioner places collectives."""
    if not 0.0 <= ema_decay < 1.0:
        raise ValueError(f"ema_decay must be in [0, 1), got {ema_decay}")
    if swa_start > 0 and ema_decay > 0.0:
        raise ValueError(
            "ema_decay and swa_start_step are mutually exclusive — both "
            "own the single averaged-params mirror")
    if swa_every < 1:
        raise ValueError(f"swa_every must be >= 1, got {swa_every}")
    if grad_accum_steps < 1:
        raise ValueError(
            f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if fused_update is not None and (ema_decay > 0.0 or swa_start > 0):
        raise ValueError(
            "train.fused_epilogue does not maintain the EMA/SWA params "
            "mirror — disable optim.ema_decay/swa_start_step or the "
            "fused epilogue")

    def transform_batch(batch, dropout_rng):
        """Per-(micro)batch input transforms, same fold-in discipline
        in every path."""
        if device_augment is not None:
            # Device-side crop/flip/RandAugment/normalize on the raw u8
            # batch (ops/device_augment.py) — same fold-in discipline as
            # dropout (deterministic under resume: same step, same
            # crops), distinct domain tag so augment draws never collide
            # with the mixup stream below.
            batch = device_augment(
                batch, jax.random.fold_in(dropout_rng, 2), train=True)
        if mixup is not None:
            batch = mixup(batch, jax.random.fold_in(dropout_rng, 1))
        if teacher_fn is not None:
            # Distillation (distill.py): the frozen teacher scores the
            # (possibly mixup-transformed) batch in the same executable;
            # the KD loss reads batch['teacher_logits'].
            batch = {**batch, "teacher_logits": teacher_fn(batch)}
        return batch

    def grad_one_batch(params, stats, batch, dropout_rng, scale):
        """grads + aux for ONE (micro)batch — the single-shot math."""

        def loss_for_grad(p):
            # LoRA et al: fold adapter leaves into base kernels in-graph
            # (lora.merge); grads flow only through the transform's
            # non-stop_gradient outputs.
            if param_transform is not None:
                p = param_transform(p)
            logits, new_stats, model_aux = apply_model(
                model, p, stats, batch, train=True,
                dropout_rng=dropout_rng,
            )
            loss, aux = loss_fn(logits, batch)
            total = loss + model_aux  # sown losses (MoE aux) join the objective
            scaled = total * scale if scale is not None else total
            return scaled, (loss, aux, model_aux, new_stats)

        return jax.grad(loss_for_grad, has_aux=True)(params)

    def accum_grads(state, batch, dropout_rng, scale):
        """lax.scan over grad_accum_steps microbatches: grads (still
        loss-scaled — the unscale happens once, after accumulation) sum
        in the carry, BN stats thread sequentially (microbatch i sees
        i-1's running stats — sequential-small-batch semantics, the
        same caveat as optax.MultiSteps), per-microbatch metrics stack
        in ys and average after."""
        k = grad_accum_steps

        def split(x):
            if x.shape[0] % k:
                # "step batch": the global batch under GSPMD jit, the
                # per-shard batch inside shard_map (the trainer
                # validates both cases at construction with the right
                # denomination — this is the trace-time backstop).
                raise ValueError(
                    f"train.grad_accum_steps={k} does not divide the "
                    f"step batch {x.shape[0]}")
            return x.reshape((k, x.shape[0] // k) + x.shape[1:])

        micro = jax.tree.map(split, batch)

        def body(carry, xs):
            grad_acc, stats = carry
            mb, idx = xs
            # Per-microbatch key: the step fold already happened; the
            # microbatch index folds on top, so draws are independent
            # across microbatches and deterministic under resume.
            mb_rng = jax.random.fold_in(dropout_rng, idx)
            mb = transform_batch(mb, mb_rng)
            grads, (loss, aux, model_aux, new_stats) = grad_one_batch(
                state.params, stats, mb, mb_rng, scale)
            if reduce_grads is not None:
                # Overlap hook: per-BUCKET collectives issued HERE, so
                # microbatch i's reductions overlap microbatch i+1's
                # compute under the latency-hiding scheduler.
                grads = reduce_grads(grads)
            grad_acc = jax.tree.map(jnp.add, grad_acc, grads)
            stats = new_stats if new_stats is not None else stats
            return (grad_acc, stats), (loss, aux, model_aux)

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        (grad_acc, stats), (losses, auxes, model_auxes) = jax.lax.scan(
            body, (zeros, state.batch_stats),
            (micro, jnp.arange(k, dtype=jnp.int32)))
        grads = jax.tree.map(lambda g: g / k, grad_acc)
        loss = jnp.mean(losses)
        aux = jax.tree.map(jnp.mean, auxes)
        model_aux = jnp.mean(model_auxes)
        new_stats = stats if state.batch_stats else None
        return grads, (loss, aux, model_aux, new_stats)

    def train_step(state: TrainState, batch: dict, rng: jax.Array):
        # Per-step dropout key: fold the step counter into the base key —
        # deterministic under resume (same step → same mask), no key chain
        # to checkpoint (the reference relies on torch's stateful global RNG).
        dropout_rng = jax.random.fold_in(rng, state.step)

        scale = state.dynamic_scale.scale if state.dynamic_scale is not None else None

        if grad_accum_steps > 1:
            grads, (loss, aux, model_aux, new_stats) = accum_grads(
                state, batch, dropout_rng, scale)
        else:
            one = transform_batch(batch, dropout_rng)
            grads, (loss, aux, model_aux, new_stats) = grad_one_batch(
                state.params, state.batch_stats, one, dropout_rng, scale)
            if reduce_grads is not None:
                grads = reduce_grads(grads)
        if reduce_grads_accum is not None:
            # Monolithic post-backward reduction (the baseline arm the
            # bucketed overlap is measured against): ONE whole-tree
            # collective on the accumulated grads.
            grads = reduce_grads_accum(grads)
        if reduce_metrics is not None:
            # shard_map: loss/metrics are per-shard means — average
            # across the batch shards so every replica logs (and the
            # sentinel judges) the same numbers the GSPMD step would.
            loss, aux, model_aux = reduce_metrics((loss, aux, model_aux))
            if new_stats is not None:
                # BN running stats averaged across replicas each step
                # (SyncBN-flavored): keeps the replicated state bitwise
                # in sync, which the replicated-DP contract requires.
                new_stats = reduce_metrics(new_stats)

        if fused_update is not None:
            return _fused_epilogue_step(
                state, grads, loss, aux, model_aux, new_stats,
                fused_update=fused_update, numeric_guard=numeric_guard,
                module_grad_norms=module_grad_norms,
                model_health=model_health)

        if state.dynamic_scale is not None:
            # GradScaler semantics (torch:amp/grad_scaler.py:302,375,484):
            # unscale, check finite, skip update on overflow, adjust scale.
            grads = jax.tree.map(lambda g: g / scale, grads)
            grads_ok = _tree_finite(grads)
            finite = grads_ok
            if numeric_guard:
                # sentinel: a finite-grads / non-finite-loss step (rare
                # but real: an inf loss whose grad zeroed out) must not
                # feed the EMA/plateau machinery a poisoned loss either.
                finite &= jnp.isfinite(loss)
            stepped = state.apply_gradients(tx, grads, new_stats,
                                            ema_decay=ema_decay,
                                            swa_start=swa_start,
                                            swa_every=swa_every, loss=loss)
            skipped = state.replace(step=state.step + 1)  # step advances either way
            new_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old), stepped, skipped
            )
            # The scaler adjusts on GRAD overflow only (GradScaler
            # semantics): a non-finite loss with finite grads skips the
            # update above but must not shrink the loss scale.
            new_state = new_state.replace(
                dynamic_scale=state.dynamic_scale.update(grads_ok)
            )
            metrics_extra = {"loss_scale": scale, "grads_finite": grads_ok}
            if numeric_guard:
                metrics_extra["update_skipped"] = 1.0 - finite.astype(
                    jnp.float32)
        elif numeric_guard:
            # Unscaled training gets the same skip-step gate (sentinel/
            # numeric guard): both branches are computed in-graph and the
            # select is elementwise — no host round-trip, no recompile.
            finite = _tree_finite(grads) & jnp.isfinite(loss)
            stepped = state.apply_gradients(tx, grads, new_stats,
                                            ema_decay=ema_decay,
                                            swa_start=swa_start,
                                            swa_every=swa_every, loss=loss)
            skipped = state.replace(step=state.step + 1)
            new_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old), stepped, skipped
            )
            metrics_extra = {
                "grads_finite": finite,
                "update_skipped": 1.0 - finite.astype(jnp.float32),
            }
        else:
            new_state = state.apply_gradients(tx, grads, new_stats,
                                              ema_decay=ema_decay,
                                              swa_start=swa_start,
                                              swa_every=swa_every,
                                              loss=loss)
            metrics_extra = {}

        gnorm = optax_global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm, "aux_loss": model_aux,
                   **aux, **metrics_extra}
        if model_health:
            # Training-dynamics pass on the ACTUAL applied update (the
            # skip-select is already folded into new_state.params);
            # supersedes the module_grad_norms loop (same grad_norm/<k>
            # keys, plus param/update norms and ratios).
            from pytorch_distributed_train_tpu.ops.model_health import (
                health_stats,
            )

            metrics.update(health_stats(grads, state.params,
                                        new_state.params))
        elif module_grad_norms:
            for key, sub in grads.items():
                metrics[f"grad_norm/{key}"] = optax_global_norm(sub)
        return new_state, metrics

    return train_step


def _fused_epilogue_step(state: TrainState, grads, loss, aux, model_aux,
                         new_stats, *, fused_update, numeric_guard: bool,
                         module_grad_norms: bool,
                         model_health: bool = False):
    """Shared tail of train_step on the fused path: loss-scale unscale +
    finite gate + clip + optimizer update in ONE pass over the grad tree
    (ops/fused_update.py), instead of the chain's three passes plus the
    whole-TrainState two-branch select. Skip/scale semantics match the
    chain path exactly: the gate selects per-leaf against the old state,
    the step counter advances either way, and the scaler adjusts on GRAD
    overflow only."""
    metrics_extra = {}
    finite = None
    new_dynamic_scale = None
    if state.dynamic_scale is not None:
        scale = state.dynamic_scale.scale
        grads = jax.tree.map(lambda g: g / scale, grads)
        grads_ok = _tree_finite(grads)
        finite = grads_ok
        if numeric_guard:
            finite = finite & jnp.isfinite(loss)
        new_dynamic_scale = state.dynamic_scale.update(grads_ok)
        metrics_extra = {"loss_scale": scale, "grads_finite": grads_ok}
        if numeric_guard:
            metrics_extra["update_skipped"] = 1.0 - finite.astype(
                jnp.float32)
    elif numeric_guard:
        finite = _tree_finite(grads) & jnp.isfinite(loss)
        metrics_extra = {
            "grads_finite": finite,
            "update_skipped": 1.0 - finite.astype(jnp.float32),
        }

    new_params, new_opt_state, gnorm = fused_update(
        grads, state.opt_state, state.params, finite=finite)
    stats = state.batch_stats
    if new_stats is not None:
        # The chain path's skip branch keeps the OLD stats (the whole
        # stepped-vs-skipped select); match it per-leaf here.
        if finite is not None:
            stats = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                new_stats, state.batch_stats)
        else:
            stats = new_stats
    new_state = state.replace(
        step=state.step + 1, params=new_params, opt_state=new_opt_state,
        batch_stats=stats)
    if new_dynamic_scale is not None:
        new_state = new_state.replace(dynamic_scale=new_dynamic_scale)
    metrics = {"loss": loss, "grad_norm": gnorm, "aux_loss": model_aux,
               **aux, **metrics_extra}
    if model_health:
        from pytorch_distributed_train_tpu.ops.model_health import (
            health_stats,
        )

        metrics.update(health_stats(grads, state.params, new_params))
    elif module_grad_norms:
        for key, sub in grads.items():
            metrics[f"grad_norm/{key}"] = optax_global_norm(sub)
    return new_state, metrics


def optax_global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )


def make_eval_step(model, loss_fn: Callable,
                   schedule_free: bool = False,
                   param_transform: Callable | None = None,
                   teacher_fn: Callable | None = None,
                   device_augment=None) -> Callable:
    def eval_step(state: TrainState, batch: dict):
        if device_augment is not None:
            # eval ships raw u8 too; the transform reduces to the
            # deterministic normalize (no draws — rng unused).
            batch = device_augment(batch, None, train=False)
        if teacher_fn is not None:
            # losses that SCORE AGAINST a frozen model (DPO's reference)
            # need its logits at eval time too
            batch = {**batch, "teacher_logits": teacher_fn(batch)}
        params = state.eval_params
        if schedule_free:
            # Schedule-Free trains on the z-sequence; the model that's
            # actually good is the x/y interpolation recovered from the
            # optimizer state (optim.schedule_free_eval locates the
            # ScheduleFreeState inside the chain).
            from pytorch_distributed_train_tpu.optim import (
                schedule_free_eval,
            )

            params = schedule_free_eval(state.opt_state, params)
        if param_transform is not None:
            params = param_transform(params)
        logits, _, _ = apply_model(
            # eval_batch_stats: the EMA stats mirror when EMA is on —
            # averaged weights + trajectory stats mis-normalize BN models
            model, params, state.eval_batch_stats, batch,
            train=False, dropout_rng=None,
        )
        loss, aux = loss_fn(logits, batch)
        return {"loss": loss, **aux}

    return eval_step


# ---------------------------------------------------------------- sharding

def offload_state_shardings(state_sharding) -> Any:
    """ZeRO-Offload analogue (DeepSpeed concept; torch FSDP
    CPUOffload(offload_params=) is the in-reference-library cousin): return
    a copy of the TrainState sharding pytree whose OPTIMIZER-STATE subtree
    lives in ``pinned_host`` memory. Between steps the adam/lamb moments sit
    in host DRAM instead of HBM; the train step stages them in and out with
    in-graph ``jax.device_put`` and XLA overlaps the transfers with compute.
    Partition specs are preserved — each host holds exactly the shards its
    devices would have held.

    TPU-only at runtime: the CPU backend has no implementation for the
    placement custom-call (tests cover the metadata transform; the axon TPU
    executes it)."""
    to_host = lambda s: NamedSharding(  # noqa: E731
        s.mesh, s.spec, memory_kind="pinned_host")
    return state_sharding.replace(
        opt_state=jax.tree.map(to_host, state_sharding.opt_state))


def offload_opt_state(train_step, opt_dev_sharding, opt_host_sharding):
    """Wrap a train step for offloaded optimizer state: stage the moments
    HBM-ward before the update and back to pinned host after. Both sharding
    pytrees are closure constants, so the transfers compile into the one
    step executable (no per-step host round-trip in Python)."""

    def wrapped(state: TrainState, batch: dict, rng: jax.Array):
        state = state.replace(
            opt_state=jax.device_put(state.opt_state, opt_dev_sharding))
        new_state, metrics = train_step(state, batch, rng)
        new_state = new_state.replace(
            opt_state=jax.device_put(new_state.opt_state, opt_host_sharding))
        return new_state, metrics

    return wrapped


def _drop_axis(spec: PartitionSpec, axis: str) -> PartitionSpec:
    """Remove one mesh axis from a PartitionSpec (entries may be tuples)."""
    out = []
    for e in spec:
        if e == axis:
            out.append(None)
        elif isinstance(e, tuple):
            kept = tuple(a for a in e if a != axis)
            out.append(kept if kept else None)
        else:
            out.append(e)
    return PartitionSpec(*out)


def state_shardings(mesh: Mesh, rules, state_shape,
                    zero_stage: int = 3) -> Any:
    """Sharding pytree for a TrainState *shape* tree (from jax.eval_shape).

    One rules table covers params, optimizer mirrors (mu/nu/trace/MultiSteps
    accumulators — same name suffixes), and batch stats (fall through to the
    catch-all → replicated). Divisibility-validated against the mesh.

    ``zero_stage`` selects the torch-FSDP ShardingStrategy analogue on the
    'fsdp' mesh axis (SURVEY C13 `ShardingStrategy{FULL_SHARD,NO_SHARD}`):

    - 3 (default, FULL_SHARD/ZeRO-3): params AND optimizer mirrors sharded
      per the rules — XLA all-gathers weights at use.
    - 1 (ZeRO-1, torch's optimizer-state sharding): params (and the EMA
      mirror) REPLICATED over 'fsdp' — it behaves as a second data axis
      for compute — while optimizer moments keep the sharded layout; the
      partitioner derives the reduce-scatter(grads) -> sharded update ->
      all-gather(params) dance that ZeRO-1 implements by hand. Weight
      memory is not reduced, optimizer memory (2x params for adam) is.

    NO_SHARD is simply fsdp=1; there is no runtime to choose, only layout.
    """
    if zero_stage not in (1, 3):
        raise ValueError(f"zero_stage must be 1 or 3, got {zero_stage}")
    sh = rules.tree_shardings(mesh, state_shape)
    if zero_stage == 1:
        def replicate_fsdp(s):
            return NamedSharding(mesh, _drop_axis(s.spec, "fsdp"))

        sh = sh.replace(params=jax.tree.map(replicate_fsdp, sh.params))
        if sh.ema_params is not None:
            sh = sh.replace(
                ema_params=jax.tree.map(replicate_fsdp, sh.ema_params))
    return sh


def jit_train_step(train_step, mesh: Mesh, state_sharding, batch_axes=("data", "fsdp")):
    batch_sh = NamedSharding(mesh, PartitionSpec(tuple(batch_axes)))
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.jit(
        train_step,
        in_shardings=(state_sharding, batch_sh, rep),
        out_shardings=(state_sharding, rep),
        donate_argnums=(0,),
    )


def jit_eval_step(eval_step, mesh: Mesh, state_sharding, batch_axes=("data", "fsdp")):
    batch_sh = NamedSharding(mesh, PartitionSpec(tuple(batch_axes)))
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.jit(
        eval_step,
        in_shardings=(state_sharding, batch_sh),
        out_shardings=rep,
    )


# ------------------------------------------- overlapped grad collectives
#
# The DDP-reducer analogue (SURVEY [TORCH] reducer.hpp:285): under
# shard_map data parallelism the gradient reduction moves out of the
# monolithic post-backward psum into per-BUCKET pmeans issued inside the
# accumulation scan — bucketed by REVERSE parameter order (the order
# backward produces grads), sized by train.grad_bucket_mb — so the
# collectives for microbatch i overlap microbatch i+1's remaining
# compute once XLA's latency-hiding scheduler is on.

# (LATENCY_HIDING_XLA_FLAGS — the scheduler preset the overlap path
# wants in XLA_FLAGS before backend init — is re-exported from
# config.py via the module imports above: the torch-world analogue is
# NCCL's stream overlap, which DDP gets for free from autograd hooks;
# XLA needs the scheduler told to hide collective latency behind
# compute. bench.py applies it pre-import; trainer runs export it in
# the launcher environment — docs/performance.md.)




def overlap_grad_reducer(params_tree, bucket_mb: int, axis_names):
    """Per-microbatch bucketed reducer (the ``reduce_grads`` hook):
    returns (reduce_fn, buckets). Buckets come from
    parallel.partition.grad_buckets over the params SHAPE tree —
    reverse parameter order, ~bucket_mb each, mirroring DDP's
    ``bucket_cap_mb``; each bucket reduces as ONE tupled pmean, i.e.
    one collective the scheduler can hide behind the next microbatch's
    compute."""
    from pytorch_distributed_train_tpu.parallel.partition import (
        grad_buckets,
    )

    buckets = grad_buckets(params_tree, bucket_mb * 2**20)
    axes = tuple(axis_names)

    def reduce_fn(grads):
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        out = list(leaves)
        for bucket in buckets:
            reduced = jax.lax.pmean(
                tuple(leaves[i] for i in bucket), axes)
            for j, i in enumerate(bucket):
                out[i] = reduced[j]
        return jax.tree_util.tree_unflatten(treedef, out)

    return reduce_fn, buckets


def monolithic_grad_reducer(axis_names):
    """The baseline arm: ONE whole-tree pmean on the ACCUMULATED grads
    (the ``reduce_grads_accum`` hook) — what a hand-written post-
    backward all-reduce does, and what the bucketed in-scan reduction
    is A/B'd against (tools/aot_ab.py ``overlap`` arm)."""
    axes = tuple(axis_names)

    def reduce_fn(grads):
        return jax.lax.pmean(grads, axes)

    return reduce_fn


def metrics_reducer(axis_names):
    """Cross-shard mean for per-shard loss/metrics/batch-stats (the
    ``reduce_metrics`` hook)."""
    axes = tuple(axis_names)

    def reduce_fn(tree):
        return jax.lax.pmean(tree, axes)

    return reduce_fn


def assert_replicated_for_overlap(state_sharding) -> None:
    """The overlap path is the DDP analogue: pure data parallelism with
    the whole TrainState REPLICATED (the batch axes act as data axes
    only). A sharded param/opt leaf would silently compute garbage
    inside the full-manual shard_map body — refuse loudly instead."""
    bad = []
    flat, _ = jax.tree_util.tree_flatten_with_path(state_sharding)
    for path, sh in flat:
        if hasattr(sh, "is_fully_replicated") and not sh.is_fully_replicated:
            from pytorch_distributed_train_tpu.parallel.partition import (
                path_name,
            )

            bad.append(path_name(path))
    if bad:
        raise ValueError(
            "train.overlap_collectives needs the whole TrainState "
            "replicated (pure data parallelism — set mesh.fsdp=1 or a "
            f"replicating rule set); sharded leaves: {bad[:5]}"
            f"{'...' if len(bad) > 5 else ''}")


def shard_rng_fold(rng: jax.Array, axis_names) -> jax.Array:
    """Per-shard PRNG key inside a shard_map body: fold the linearized
    shard index over ``axis_names`` into the (replicated) key. Without
    this every data-parallel replica would draw IDENTICAL dropout/
    augment/mixup randomness for its local batch — the DDP contract is
    per-rank independent draws (torch ranks each own a global-RNG
    stream). Axis sizes come from ``psum(1, ax)`` so no mesh handle is
    needed in-graph."""
    idx = jnp.int32(0)
    for ax in axis_names:
        idx = idx * jax.lax.psum(jnp.int32(1), ax) + jax.lax.axis_index(ax)
    return jax.random.fold_in(rng, idx)


def jit_overlap_train_step(train_step, mesh: Mesh, state_sharding,
                           batch_axes=("data", "fsdp")):
    """shard_map + jit wrap of a train step built with the reduce_*
    hooks: state replicated, batch sharded over ``batch_axes``, grads
    reduced explicitly inside the step body (per-bucket or monolithic —
    whichever hooks the step closed over). Buffer donation is
    preserved: the jit level aliases the replicated state exactly as
    ``jit_train_step`` does. The replicated rng is re-keyed per shard
    (``shard_rng_fold``) so dropout/augment draws are independent
    across replicas — a different stream than the GSPMD step's global-
    batch draws (both are valid samplings; parity tests compare
    deterministic configs)."""
    assert_replicated_for_overlap(state_sharding)
    from pytorch_distributed_train_tpu.utils.compat import shard_map

    axes = tuple(batch_axes)

    def sharded_step(state, batch, rng):
        return train_step(state, batch, shard_rng_fold(rng, axes))

    batch_spec = PartitionSpec(axes)
    smapped = shard_map(
        sharded_step, mesh=mesh,
        in_specs=(PartitionSpec(), batch_spec, PartitionSpec()),
        out_specs=(PartitionSpec(), PartitionSpec()),
        # Full-manual + no replication check: the body's pmeans make the
        # outputs replicated by construction; legacy jax's check_rep
        # cannot see through the scan-carried bucket reductions.
        check_vma=False)
    batch_sh = NamedSharding(mesh, batch_spec)
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.jit(
        smapped,
        in_shardings=(state_sharding, batch_sh, rep),
        out_shardings=(state_sharding, rep),
        donate_argnums=(0,),
    )
