"""Autoregressive generation with a KV cache (Llama family).

The inference counterpart of the training harness — torch-ecosystem
analogue: HF ``model.generate(past_key_values=...)``. TPU-first shape
discipline: the KV cache is a STATIC (B, max_seq_len, H_kv, D) buffer per
layer (flax 'cache' collection, models/llama.py decode mode), the prefill
is one jitted call over the whole prompt, and every subsequent token is the
same jitted single-token step — two executables total, no shape-dependent
recompilation as the sequence grows (dynamic shapes would leave the MXU —
SURVEY §7.4.5).

Sampling: greedy, temperature, and top-k — jax.random.categorical on
fp32 logits; deterministic under a fixed key.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_train_tpu.config import ModelConfig, PrecisionConfig
from pytorch_distributed_train_tpu.models.registry import build_model


def build_decode_model(model_cfg: ModelConfig, precision: PrecisionConfig):
    """The decode-mode twin of a training model: same params tree, KV cache
    enabled, remat off (pointless without a backward pass)."""
    import dataclasses

    cfg = dataclasses.replace(model_cfg, remat=False)
    if getattr(cfg, "fused_lm_loss", False):
        # generation needs logits; the fused head returns CE sums
        cfg = dataclasses.replace(cfg, fused_lm_loss=False)
    if getattr(cfg, "segment_eos_id", -1) >= 0:
        # packed-document isolation is a TRAINING feature; decode serves
        # one unpacked sequence per row, where isolation is vacuous — a
        # packed-trained config must still generate without overrides
        cfg = dataclasses.replace(cfg, segment_eos_id=-1)
    model = build_model(cfg, precision)
    if not any(f.name == "decode" for f in dataclasses.fields(model)):
        raise ValueError(
            f"model {model_cfg.name!r} has no decode mode (generation is "
            "causal-LM only)")
    return dataclasses.replace(model, decode=True)


@lru_cache(maxsize=16)
def _cache_shapes(model, batch: int):
    ids = jnp.zeros((batch, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init({"params": jax.random.PRNGKey(0)}, ids,
                           train=False))
    return shapes["cache"]


def init_cache(model, batch: int) -> Any:
    """Allocate the static KV cache for ``batch`` sequences.

    Shapes come from one memoized eval_shape per (model, batch) — no param
    re-init, no repeated full-model trace per generate() call; fresh zero
    buffers each time (the decode step donates the cache in place)."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        _cache_shapes(model, batch))


def shard_decode_params(model_name: str, mesh, params) -> Any:
    """Lay a (possibly int8-quantized) params tree out on ``mesh`` for
    multi-chip serving — the training partition rules reused for decode
    (tensor-parallel heads/MLP over 'tensor', optionally fsdp/data too).
    A quantized {w_int8, scale} struct inherits the base kernel's rule:
    the rule lookup sees the kernel path/shape (via a proxy tree, so
    unquantized 'scale' norm params still match their own rules), and the
    scale re-validates the spec against its keepdims-1 shape (non-divisible
    dims replicate). Returns the device_put tree; pass it (and mesh=) to
    ``generate``."""
    from pytorch_distributed_train_tpu import quant
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model,
        validate_spec,
    )

    rules = rules_for_model(model_name)
    is_q = quant._is_quant_leaf
    proxy = jax.tree.map(lambda x: x[quant.weight_key(x)] if is_q(x) else x,
                         params, is_leaf=is_q)
    kernel_shardings = rules.tree_shardings(mesh, proxy)

    def expand(leaf, sh):
        if not is_q(leaf):
            return sh
        wk = quant.weight_key(leaf)
        w = leaf[wk]
        scale_shape = leaf[quant._S].shape
        spec = sh.spec
        if wk == quant._W4:
            # int4 scales carry ONE extra dim (the grouped axis split to
            # (n_groups, 1)): derive their spec by splitting the kernel
            # spec's entry at that axis — group count keeps the kernel
            # dim's sharding (validate_spec replicates it when the group
            # count doesn't divide), the size-1 inner dim replicates.
            axis, _ = quant._int4_grouping(w.shape, scale_shape)
            entries = tuple(spec) + (None,) * (w.ndim - len(tuple(spec)))
            spec = P(*entries[:axis], entries[axis], None,
                     *entries[axis + 1:])
        scale_spec = validate_spec(spec, scale_shape, mesh)
        return {wk: sh,
                quant._S: NamedSharding(mesh, scale_spec)}

    sharding_tree = jax.tree.map(expand, params, kernel_shardings,
                                 is_leaf=is_q)
    return jax.device_put(params, sharding_tree)


def _cache_shardings(mesh, cache, tp_axis: str = "tensor"):
    """KV buffers (B, S, H_kv, D) shard heads over the TP axis (the cache
    must live where its heads' q/k/v columns live); everything else
    (position counters) replicates. Head counts not divisible by the axis
    replicate via validate_spec."""
    from pytorch_distributed_train_tpu.parallel.partition import validate_spec

    def one(leaf):
        if getattr(leaf, "ndim", 0) == 4:
            spec = validate_spec(P(None, None, tp_axis, None), leaf.shape,
                                 mesh)
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree.map(one, cache)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _decode_step(model, params, cache, ids):
    # Weight-only int8 support (quant.py): a quantized tree dequantizes
    # here, inside the executable — the int8 arrays are the jit inputs, so
    # they (not bf16 copies) are what sit in HBM between steps.
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, train=False,
        mutable=["cache"],
    )
    return logits[:, -1], updated["cache"]


def filter_logits(logits, temperature, top_k: int, top_p: float = 0.0,
                  min_p: float = 0.0):
    """THE sampling law's logit filtering — temperature scaling, top-k
    truncation, then top-p (nucleus) truncation. Single definition shared
    by the direct sampler below, speculative.py's draft/verify
    distributions (whose exactness guarantee is 'same law as
    generate()'), and serving.py's per-row sampler.
    ``temperature`` is a positive scalar OR an array broadcastable against
    ``logits`` (serving passes (B, 1) per-row temperatures); every entry
    must be > 0. ``top_p``/``min_p`` likewise accept a scalar or a (B, 1)
    per-row array (out-of-range array entries = disabled for that row).
    ``top_p`` in (0, 1) keeps the smallest sorted prefix
    whose cumulative probability reaches top_p (a token survives iff the
    mass strictly BEFORE it is < top_p, so the argmax always survives).
    Boundary convention: when a prefix's mass lands EXACTLY on top_p the
    next token is dropped — the same strict rule as the installed
    transformers 4.57.6 TopPLogitsWarper (ascending sort, remove iff
    inclusive-cum <= 1-top_p ⟺ keep iff exclusive-desc-mass < top_p;
    OLDER HF releases used the shifted-descending form, which kept the
    boundary token — differs only at exact fp equality). 0 disables. ``min_p`` in (0, 1) keeps tokens whose
    probability is >= min_p x the max probability (Nguyen et al. 2024 —
    an entropy-adaptive floor: permissive when the model is uncertain,
    strict when confident; applies after top-k/top-p, argmax always
    survives); 0 disables."""
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    # top_p / min_p accept a python float (static: disabled values skip
    # the work entirely at trace time) OR a traced array broadcastable
    # against (B, 1) — the serving batchers pass PER-ROW values, where
    # out-of-range entries mean "disabled" and resolve to keep-all inside
    # the graph (they can't prune the computation, only its result).
    p_static = isinstance(top_p, (int, float))
    if (not p_static) or 0.0 < top_p < 1.0:
        # Mask by SORTED INDEX, not by threshold value: ties at the
        # nucleus boundary (common in bf16 / int8-dequant logits) must not
        # widen the kept set beyond the prefix. Stable argsort breaks ties
        # by original position; the inverse permutation (argsort of the
        # ranks) scatters the sorted keep-mask back.
        p_eff = top_p if p_static else jnp.where(
            (top_p > 0.0) & (top_p < 1.0), top_p, 1.0)
        srt_idx = jnp.argsort(-logits, axis=-1)
        srt = jnp.take_along_axis(logits, srt_idx, axis=-1)
        p_srt = jax.nn.softmax(srt, axis=-1)
        before = jnp.cumsum(p_srt, axis=-1) - p_srt  # exclusive cumsum
        keep = jnp.take_along_axis(before < p_eff,
                                   jnp.argsort(srt_idx, axis=-1), axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    m_static = isinstance(min_p, (int, float))
    if (not m_static) or 0.0 < min_p < 1.0:
        m_eff = min_p if m_static else jnp.where(
            (min_p > 0.0) & (min_p < 1.0), min_p, 0.0)
        probs = jax.nn.softmax(logits, axis=-1)
        floor = m_eff * jnp.max(probs, axis=-1, keepdims=True)
        logits = jnp.where(probs >= floor, logits, -jnp.inf)
    return logits


def apply_penalties(logits, counts, *, gen_counts=None,
                    repetition_penalty: float = 1.0,
                    presence_penalty: float = 0.0,
                    frequency_penalty: float = 0.0):
    """Context-aware logit penalties, applied on RAW logits BEFORE the
    temperature/top-k/top-p warpers (HF's processor-before-warper order).

    Two count tensors because the two conventions score different text:
    - ``counts`` (B, V): prompt + generated — HF's repetition_penalty
      considers the full context.
    - ``gen_counts`` (B, V): GENERATED tokens only — the OpenAI/vLLM
      presence/frequency penalties never score the prompt (an
      OpenAI-compatible server must not penalize a token merely for
      appearing in the user's input). Defaults to ``counts`` for callers
      that deliberately share one context; generate()/serving pass the
      split for OpenAI parity.

    Penalties may be scalars or (B,)/(B, 1) arrays (serving passes
    per-request values):
    - repetition_penalty (HF CTRL rule, >1 discourages): seen tokens'
      positive logits divide by p, negative multiply by p.
    - presence_penalty (OpenAI, additive): subtract p once for any
      generated token.
    - frequency_penalty (OpenAI, additive): subtract p x generated count.
    """
    logits = logits.astype(jnp.float32)
    seen = counts > 0
    gc = counts if gen_counts is None else gen_counts

    def bcol(p):  # scalar or (B,)/(B,1) → broadcastable against (B, V)
        p = jnp.asarray(p, jnp.float32)
        return p[:, None] if p.ndim == 1 else p

    rp = bcol(repetition_penalty)
    penalized = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen & (rp != 1.0), penalized, logits)
    logits = logits - bcol(presence_penalty) * (gc > 0).astype(jnp.float32)
    logits = logits - bcol(frequency_penalty) * gc
    return logits


def token_counts(ids, vocab_size: int, pad_id: int | None = None):
    """(B, S) ids → (B, V) fp32 occurrence counts (the `counts` input of
    apply_penalties). ``pad_id`` rows are excluded (right-padded
    prompts must not penalize the pad token)."""
    ids = jnp.asarray(ids, jnp.int32)
    w = jnp.ones(ids.shape, jnp.float32)
    if pad_id is not None:
        w = jnp.where(ids == pad_id, 0.0, w)
    B = ids.shape[0]
    counts = jnp.zeros((B, vocab_size), jnp.float32)
    return counts.at[jnp.arange(B)[:, None], ids].add(w)


def bump_counts(counts, tok):
    """Add one emitted token per row to the (B, V) counts."""
    return counts.at[jnp.arange(counts.shape[0]), tok].add(1.0)


def bias_vector(logit_bias: dict, vocab_size: int):
    """OpenAI ``logit_bias`` ({token_id: bias in [-100, 100]}) → a (V,)
    fp32 vector added to the logits AFTER penalties, before the
    temperature/top-k/top-p warpers. -100 is a practical ban, +100 a
    practical force (exclusive selection)."""
    v = np.zeros((vocab_size,), np.float32)
    for i, b in validate_logit_bias(logit_bias, vocab_size).items():
        v[i] = b
    return jnp.asarray(v)


def validate_logit_bias(logit_bias: dict, vocab_size: int
                        ) -> dict[int, float]:
    """ONE definition of the OpenAI logit_bias contract (ids in
    [0, vocab), values in [-100, 100] — out-of-range is an error, not a
    silent super-ban), shared by bias_vector and serving's submit so the
    two admission paths can never diverge. Returns normalized
    {int id: float bias}."""
    out: dict[int, float] = {}
    for k, b in logit_bias.items():
        i = int(k)
        if not 0 <= i < vocab_size:
            raise ValueError(
                f"logit_bias token id {i} out of range [0, {vocab_size})")
        b = float(b)
        if not -100.0 <= b <= 100.0:
            raise ValueError(
                f"logit_bias value {b} for token {i} outside [-100, 100]")
        out[i] = b
    return out


def _sample(logits, rng, temperature: float, top_k: int,
            top_p: float = 0.0, min_p: float = 0.0):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, filter_logits(logits, temperature, top_k, top_p, min_p),
        axis=-1).astype(jnp.int32)


def generate(model, params, prompt_ids, max_new_tokens: int,
             *, temperature: float = 0.0, top_k: int = 0,
             top_p: float = 0.0, min_p: float = 0.0, rng=None,
             eos_id: int | None = None, mesh=None,
             repetition_penalty: float = 1.0,
             presence_penalty: float = 0.0,
             frequency_penalty: float = 0.0,
             logit_bias: dict | None = None,
             pad_id: int | None = None) -> jnp.ndarray:
    """Generate continuations for a (B, S) int32 prompt batch.

    Returns (B, S + max_new_tokens) ids. Prefill consumes the prompt in one
    call; each new token reuses the jitted single-token step (cache donated
    in-place). Decode contract (models/llama.py): a multi-token call means
    "prefill this cache from position 0"; continuation past a prefill is
    single-token steps only. With ``temperature=0`` decoding is greedy and
    deterministic; ``eos_id`` freezes finished rows (emitted tokens stay
    ``eos_id``). Repetition/presence/frequency penalties follow
    :func:`apply_penalties` — repetition scores prompt+generated (HF),
    presence/frequency score generated tokens only (OpenAI/vLLM); active
    only when set — the off path adds no per-step work. ``pad_id``
    (default: ``eos_id``) is excluded from the prompt's repetition
    context so right-padded batches don't penalize the pad token.
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, S = prompt_ids.shape
    if S + max_new_tokens > model.max_seq_len:
        raise ValueError(
            f"prompt ({S}) + new tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({model.max_seq_len})")
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    if mesh is not None:
        # Multi-chip serving: params were laid out by shard_decode_params;
        # allocate the cache DIRECTLY into its mesh layout (heads beside
        # their q/k/v columns — materializing it on one chip first would
        # defeat the point for serving-sized caches) and replicate the
        # ids; GSPMD propagates the layouts through the same jitted step.
        shapes = _cache_shapes(model, B)
        cache = jax.jit(
            lambda: jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                 shapes),
            out_shardings=_cache_shardings(mesh, shapes),
        )()
        prompt_ids = jax.device_put(prompt_ids, NamedSharding(mesh, P()))
    else:
        cache = init_cache(model, B)
    logits, cache = _decode_step(model, params, cache, prompt_ids)  # prefill

    if repetition_penalty <= 0.0:
        raise ValueError("repetition_penalty must be > 0 (1.0 = off)")
    penalized = (repetition_penalty != 1.0 or presence_penalty != 0.0
                 or frequency_penalty != 0.0)
    # Prompt tokens feed ONLY the repetition context (counts); the OpenAI
    # additive penalties score generated tokens (gen_counts), which start
    # empty. Pad/eos exclusion keeps right-padded rows from penalizing
    # the pad token on every step.
    _pad = pad_id if pad_id is not None else eos_id
    counts = (token_counts(prompt_ids, logits.shape[-1], pad_id=_pad)
              if penalized else None)
    gen_counts = jnp.zeros_like(counts) if penalized else None
    bias = (bias_vector(logit_bias, logits.shape[-1])
            if logit_bias else None)
    out = [prompt_ids]
    done = jnp.zeros((B,), bool)
    for i in range(max_new_tokens):
        rng, step_rng = jax.random.split(rng)
        if penalized:
            logits = apply_penalties(
                logits, counts, gen_counts=gen_counts,
                repetition_penalty=repetition_penalty,
                presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty)
        if bias is not None:
            logits = logits + bias[None, :]
        nxt = _sample(logits, step_rng, temperature, top_k, top_p, min_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        if penalized:
            counts = bump_counts(counts, nxt)
            gen_counts = bump_counts(gen_counts, nxt)
        out.append(nxt[:, None])
        if i + 1 < max_new_tokens:  # last sample needs no further forward
            logits, cache = _decode_step(model, params, cache, nxt[:, None])
    return jnp.concatenate(out, axis=1)


# ------------------------------------------------------- encoder-decoder (t5)

@lru_cache(maxsize=16)
def _seq2seq_cache_shapes(decoder, batch: int, enc_shape, enc_dtype: str):
    """Memoized like _cache_shapes: one abstract decoder-init trace per
    (decoder, batch, encoder-shape), not one per generate call."""
    return jax.eval_shape(
        lambda ids, e, m: decoder.init(
            {"params": jax.random.PRNGKey(0)}, ids, e, m),
        jax.ShapeDtypeStruct((batch, 1), jnp.int32),
        jax.ShapeDtypeStruct(tuple(enc_shape), jnp.dtype(enc_dtype)),
        jax.ShapeDtypeStruct((batch, enc_shape[1]), jnp.int32),
    )["cache"]


@partial(jax.jit, static_argnums=(0,))
def _seq2seq_encode(model, params, ids, mask):
    """Jitted encoder prefill — one dispatch, int8-aware like the
    decode steps (quantized trees dequantize in-graph)."""
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    return model.apply({"params": params}, ids, attention_mask=mask)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _seq2seq_decode_step(model, params, cache, ids, enc, enc_mask):
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, enc, enc_mask,
        mutable=["cache"],
    )
    return logits[:, -1], updated["cache"]


def _seq2seq_setup(model_cfg, precision, params, input_ids,
                   max_new_tokens: int, attention_mask):
    """Shared greedy/beam seq2seq bring-up: validate the token budget,
    default the source mask, run the jitted encoder once, and build the
    cached decoder. Callers allocate their own zeroed cache (its batch
    dim differs: B rows for greedy, num_beams for beam search) via
    _alloc_cache; it is sized to max_seq_len (not the call's token
    budget) — the decode module is a static jit key, so a fixed size
    means ONE compiled step per model regardless of requested length.
    Returns (decoder, enc, attention_mask)."""
    from pytorch_distributed_train_tpu.models.t5 import (
        t5_decode_step,
        t5_encoder,
    )

    dtype = jnp.dtype(precision.compute_dtype)
    param_dtype = jnp.dtype(precision.param_dtype)
    if max_new_tokens + 1 > model_cfg.max_seq_len:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) + start token exceeds "
            f"max_seq_len ({model_cfg.max_seq_len})")
    if attention_mask is not None:
        attention_mask = jnp.asarray(attention_mask, jnp.int32)
    else:
        attention_mask = jnp.ones_like(input_ids)
    encoder = t5_encoder(model_cfg, dtype, param_dtype)
    enc = _seq2seq_encode(encoder, params, input_ids, attention_mask)
    decoder = t5_decode_step(model_cfg, dtype, param_dtype,
                             max_decode_len=model_cfg.max_seq_len)
    return decoder, enc, attention_mask


def _alloc_cache(decoder, batch: int, enc):
    shapes = _seq2seq_cache_shapes(decoder, batch, enc.shape,
                                   str(enc.dtype))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def generate_seq2seq(model_cfg, precision, params, input_ids,
                     max_new_tokens: int, *, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 0.0,
                     min_p: float = 0.0, rng=None,
                     eos_id: int | None = 1, decoder_start_id: int = 0,
                     attention_mask=None) -> jnp.ndarray:
    """Encoder-decoder generation (t5): encode the (B, Se) source once,
    then decode autoregressively with a cached decoder
    (models/t5.py::T5DecodeStep — same param tree as training).

    Returns (B, max_new_tokens) decoder tokens (no BOS column). T5's
    conventions by default: decoder starts from the pad id 0, eos is 1.
    Rows freeze at ``eos_id`` once emitted.
    """
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B = input_ids.shape[0]
    decoder, enc, attention_mask = _seq2seq_setup(
        model_cfg, precision, params, input_ids, max_new_tokens,
        attention_mask)
    cache = _alloc_cache(decoder, B, enc)

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.full((B, 1), decoder_start_id, jnp.int32)
    out = []
    done = jnp.zeros((B,), bool)
    for _ in range(max_new_tokens):
        logits, cache = _seq2seq_decode_step(
            decoder, params, cache, ids, enc, attention_mask)
        rng, step_rng = jax.random.split(rng)
        nxt = _sample(logits, step_rng, temperature, top_k, top_p, min_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        out.append(nxt[:, None])
        ids = nxt[:, None]
    return jnp.concatenate(out, axis=1)


# ------------------------------------------------------------- beam search

def _beam_expand(logp, beam_scores, finished, last_token, num_beams: int):
    """THE beam-expansion law, shared by the causal and seq2seq steps:
    finished beams are frozen (their single candidate repeats
    ``last_token`` at zero added score), live beams fan out over the
    vocab, and the global top ``num_beams`` survive. Returns
    (top_scores, parent, token)."""
    V = logp.shape[-1]
    frozen_rows = jax.vmap(lambda t: jnp.full((V,), -jnp.inf)
                           .at[t].set(0.0))(last_token)
    logp = jnp.where(finished[:, None], frozen_rows, logp)
    total = beam_scores[:, None] + logp                  # (beams, V)
    top_scores, top_idx = jax.lax.top_k(total.reshape(-1), num_beams)
    return top_scores, top_idx // V, (top_idx % V).astype(jnp.int32)


def _gather_beams(cache, parent):
    """REORDER a KV cache so each surviving beam sits on the cache row of
    its parent (gather on the batch axis — the TPU-friendly equivalent of
    torch's `reorder_cache`)."""
    return jax.tree.map(
        lambda x: jnp.take(x, parent, axis=0) if x.ndim > 0 else x, cache)


@partial(jax.jit, static_argnums=(0, 5), donate_argnums=(2,))
def _beam_step(model, params, cache, ids, beam_scores, num_beams: int,
               finished, last_token):
    """One causal-LM beam expansion (see _beam_expand/_gather_beams)."""
    from pytorch_distributed_train_tpu import quant

    p = quant.dequantize_tree(params, model.dtype)
    logits, cache = model.apply(
        {"params": p, "cache": cache}, ids, train=False, mutable=["cache"],
    )
    logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), -1)
    top_scores, parent, token = _beam_expand(
        logp, beam_scores, finished, last_token, num_beams)
    return _gather_beams(cache["cache"], parent), token, top_scores, parent


@partial(jax.jit, static_argnums=(0, 5), donate_argnums=(2,))
def _seq2seq_beam_step(decoder, params, cache, ids, beam_scores,
                       num_beams: int, finished, last_token, enc, enc_mask):
    """One encoder-decoder beam expansion: the decoder cache reorders by
    parent; the encoder rows are FIXED (every beam reads the same source,
    already repeated to the beam count) so they need no gather."""
    from pytorch_distributed_train_tpu import quant

    p = quant.dequantize_tree(params, decoder.dtype)
    logits, updated = decoder.apply(
        {"params": p, "cache": cache}, ids, enc, enc_mask,
        mutable=["cache"],
    )
    logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), -1)
    top_scores, parent, token = _beam_expand(
        logp, beam_scores, finished, last_token, num_beams)
    return _gather_beams(updated["cache"], parent), token, top_scores, parent


def _run_beam_loop(expand, first_logp, num_beams: int, max_new_tokens: int,
                   eos_id: int | None, length_penalty: float):
    """Host-side beam bookkeeping shared by causal and seq2seq search.

    ``expand(last_tokens, scores, finished) -> (token, scores, parent)``
    advances the device state (cache reorder included) one step.
    Seeds from ONE row's top-k of ``first_logp`` (all beams start
    identical — seeding per-row would make every beam pick the same
    argmax), then runs parent-pointer bookkeeping and backtracks.
    Returns (seqs (num_beams, n_steps), scores (num_beams,)) best-first;
    n_steps may stop short of max_new_tokens when every beam froze."""
    scores, first = jax.lax.top_k(first_logp, num_beams)
    tokens = [first.astype(jnp.int32)]
    parents = []
    finished = (first == eos_id) if eos_id is not None else jnp.zeros(
        (num_beams,), bool)
    gen_len = jnp.ones((num_beams,), jnp.int32)
    for _ in range(max_new_tokens - 1):
        tok, scores, parent = expand(tokens[-1], scores, finished)
        finished = jnp.take(finished, parent) if eos_id is not None \
            else finished
        gen_len = jnp.take(gen_len, parent) + (~finished).astype(jnp.int32)
        if eos_id is not None:
            finished = finished | (tok == eos_id)
        tokens.append(tok)
        parents.append(parent)
        if eos_id is not None and bool(jnp.all(finished)):
            break
    # backtrack through the parent pointers to reconstruct sequences
    n_steps = len(tokens)
    seqs = np.zeros((num_beams, n_steps), np.int32)
    idx = np.arange(num_beams)
    for t in range(n_steps - 1, -1, -1):
        seqs[:, t] = np.asarray(tokens[t])[idx]
        if t > 0:
            idx = np.asarray(parents[t - 1])[idx]
    final = np.asarray(scores) / np.maximum(
        np.asarray(gen_len), 1) ** length_penalty
    order = np.argsort(-final)
    return seqs[order], final[order]


def beam_search(model, params, prompt_ids, max_new_tokens: int,
                *, num_beams: int = 4, eos_id: int | None = None,
                length_penalty: float = 1.0) -> tuple:
    """Beam-search decoding for a (1, S) prompt (causal-LM families).

    Returns (sequences (num_beams, S + max_new_tokens), scores
    (num_beams,)) sorted best-first; ``scores`` are summed token
    log-probs divided by (generated length)**length_penalty. Beams that
    emit ``eos_id`` freeze (their score stops accumulating). num_beams=1
    reproduces greedy decoding exactly.
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, S = prompt_ids.shape
    if B != 1:
        raise ValueError(f"beam_search expects a single prompt (got B={B})")
    if S + max_new_tokens > model.max_seq_len:
        raise ValueError(
            f"prompt ({S}) + new tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({model.max_seq_len})")
    # Prefill ONCE at B=1, then broadcast the cache rows to the beam
    # count (same batch-axis gather the per-step reorder uses) — running
    # num_beams identical prompt forwards would multiply prefill cost.
    cache = init_cache(model, 1)
    logits, cache = _decode_step(model, params, cache, prompt_ids)
    cache = _gather_beams(cache, jnp.zeros((num_beams,), jnp.int32))
    # _decode_step already sliced to the last position: logits is (B, V)
    logp0 = jax.nn.log_softmax(logits[0].astype(jnp.float32), -1)

    state = {"cache": cache}

    def expand(last_tok, scores, finished):
        state["cache"], tok, scores, parent = _beam_step(
            model, params, state["cache"], last_tok[:, None], scores,
            num_beams, finished, last_tok)
        return tok, scores, parent

    seqs, final = _run_beam_loop(expand, logp0, num_beams, max_new_tokens,
                                 eos_id, length_penalty)
    full = np.concatenate(
        [np.repeat(np.asarray(prompt_ids), num_beams, 0), seqs], axis=1)
    if full.shape[1] < S + max_new_tokens:  # early eos stop: pad
        pad = np.full((num_beams, S + max_new_tokens - full.shape[1]),
                      eos_id if eos_id is not None else 0, np.int32)
        full = np.concatenate([full, pad], axis=1)
    return jnp.asarray(full), jnp.asarray(final)


def beam_search_seq2seq(model_cfg, precision, params, input_ids,
                        max_new_tokens: int, *, num_beams: int = 4,
                        eos_id: int | None = 1, length_penalty: float = 1.0,
                        decoder_start_id: int = 0,
                        attention_mask=None) -> tuple:
    """Beam-search decoding for an encoder-decoder (t5) over ONE source.

    Encodes the (1, Se) source once, repeats the encoder rows to the beam
    count (they are read-only — no per-step gather), and expands the
    cached decoder with the same beam law as the causal path. Returns
    (sequences (num_beams, max_new_tokens), scores) best-first, T5
    conventions by default (start from pad id 0, eos 1); no BOS column,
    like generate_seq2seq. num_beams=1 reproduces greedy decoding.
    """
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if input_ids.shape[0] != 1:
        raise ValueError(
            f"beam_search_seq2seq expects a single source "
            f"(got B={input_ids.shape[0]})")
    decoder, enc, attention_mask = _seq2seq_setup(
        model_cfg, precision, params, input_ids, max_new_tokens,
        attention_mask)
    enc = jnp.repeat(enc, num_beams, axis=0)
    enc_mask = jnp.repeat(attention_mask, num_beams, axis=0)
    cache = _alloc_cache(decoder, num_beams, enc)
    # Step every (identical) beam row through the start token — the rows
    # stay identical, so no gather is needed before the first expansion.
    start = jnp.full((num_beams, 1), decoder_start_id, jnp.int32)
    logits, cache = _seq2seq_decode_step(
        decoder, params, cache, start, enc, enc_mask)
    logp0 = jax.nn.log_softmax(logits[0].astype(jnp.float32), -1)

    state = {"cache": cache}

    def expand(last_tok, scores, finished):
        state["cache"], tok, scores, parent = _seq2seq_beam_step(
            decoder, params, state["cache"], last_tok[:, None], scores,
            num_beams, finished, last_tok, enc, enc_mask)
        return tok, scores, parent

    seqs, final = _run_beam_loop(expand, logp0, num_beams, max_new_tokens,
                                 eos_id, length_penalty)
    if seqs.shape[1] < max_new_tokens:  # early eos stop: pad
        pad = np.full((num_beams, max_new_tokens - seqs.shape[1]),
                      eos_id if eos_id is not None else 0, np.int32)
        seqs = np.concatenate([seqs, pad], axis=1)
    return jnp.asarray(seqs), jnp.asarray(final)
