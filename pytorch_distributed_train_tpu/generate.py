"""Autoregressive generation with a KV cache (Llama family).

The inference counterpart of the training harness — torch-ecosystem
analogue: HF ``model.generate(past_key_values=...)``. TPU-first shape
discipline: the KV cache is a STATIC (B, max_seq_len, H_kv, D) buffer per
layer (flax 'cache' collection, models/llama.py decode mode), the prefill
is one jitted call over the whole prompt, and every subsequent token is the
same jitted single-token step — two executables total, no shape-dependent
recompilation as the sequence grows (dynamic shapes would leave the MXU —
SURVEY §7.4.5).

Sampling: greedy, temperature, and top-k — jax.random.categorical on
fp32 logits; deterministic under a fixed key.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_train_tpu.config import ModelConfig, PrecisionConfig
from pytorch_distributed_train_tpu.models.registry import build_model


def build_decode_model(model_cfg: ModelConfig, precision: PrecisionConfig):
    """The decode-mode twin of a training model: same params tree, KV cache
    enabled, remat off (pointless without a backward pass)."""
    import dataclasses

    cfg = dataclasses.replace(model_cfg, remat=False)
    if getattr(cfg, "fused_lm_loss", False):
        # generation needs logits; the fused head returns CE sums
        cfg = dataclasses.replace(cfg, fused_lm_loss=False)
    model = build_model(cfg, precision)
    if not any(f.name == "decode" for f in dataclasses.fields(model)):
        raise ValueError(
            f"model {model_cfg.name!r} has no decode mode (generation is "
            "causal-LM only)")
    return dataclasses.replace(model, decode=True)


@lru_cache(maxsize=16)
def _cache_shapes(model, batch: int):
    ids = jnp.zeros((batch, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init({"params": jax.random.PRNGKey(0)}, ids,
                           train=False))
    return shapes["cache"]


def init_cache(model, batch: int) -> Any:
    """Allocate the static KV cache for ``batch`` sequences.

    Shapes come from one memoized eval_shape per (model, batch) — no param
    re-init, no repeated full-model trace per generate() call; fresh zero
    buffers each time (the decode step donates the cache in place)."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        _cache_shapes(model, batch))


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _decode_step(model, params, cache, ids):
    # Weight-only int8 support (quant.py): a quantized tree dequantizes
    # here, inside the executable — the int8 arrays are the jit inputs, so
    # they (not bf16 copies) are what sit in HBM between steps.
    from pytorch_distributed_train_tpu import quant

    params = quant.dequantize_tree(params, model.dtype)
    logits, updated = model.apply(
        {"params": params, "cache": cache}, ids, train=False,
        mutable=["cache"],
    )
    return logits[:, -1], updated["cache"]


def _sample(logits, rng, temperature: float, top_k: int):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(model, params, prompt_ids, max_new_tokens: int,
             *, temperature: float = 0.0, top_k: int = 0,
             rng=None, eos_id: int | None = None) -> jnp.ndarray:
    """Generate continuations for a (B, S) int32 prompt batch.

    Returns (B, S + max_new_tokens) ids. Prefill consumes the prompt in one
    call; each new token reuses the jitted single-token step (cache donated
    in-place). Decode contract (models/llama.py): a multi-token call means
    "prefill this cache from position 0"; continuation past a prefill is
    single-token steps only. With ``temperature=0`` decoding is greedy and
    deterministic; ``eos_id`` freezes finished rows (emitted tokens stay
    ``eos_id``).
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, S = prompt_ids.shape
    if S + max_new_tokens > model.max_seq_len:
        raise ValueError(
            f"prompt ({S}) + new tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({model.max_seq_len})")
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    cache = init_cache(model, B)
    logits, cache = _decode_step(model, params, cache, prompt_ids)  # prefill

    out = [prompt_ids]
    done = jnp.zeros((B,), bool)
    for i in range(max_new_tokens):
        rng, step_rng = jax.random.split(rng)
        nxt = _sample(logits, step_rng, temperature, top_k)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        out.append(nxt[:, None])
        if i + 1 < max_new_tokens:  # last sample needs no further forward
            logits, cache = _decode_step(model, params, cache, nxt[:, None])
    return jnp.concatenate(out, axis=1)
