"""ctypes bindings for the native image-augment kernels (native/imgops.cpp).

Replaces the Python per-image crop/flip loop and uint8→float32 math in the
input pipeline (SURVEY C17 / §7.4 hard part #1 — host-side throughput).
``available()`` gates use: callers fall back to the numpy path when the
toolchain is missing, so the pipeline never hard-depends on the build.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False


def _lib():
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        try:
            from pytorch_distributed_train_tpu.native import build_library

            lib = ctypes.CDLL(build_library("imgops"))
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            lib.imgops_augment_batch.argtypes = [
                u8p, f32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, i32p, i32p, u8p, f32p, f32p,
                ctypes.c_int]
            lib.imgops_normalize_batch.argtypes = [
                u8p, f32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, f32p, f32p, ctypes.c_int]
            _LIB = lib
        except (RuntimeError, OSError):
            _LIB = None
    return _LIB


def available() -> bool:
    return _lib() is not None


def default_threads() -> int:
    # PDTT_NATIVE_THREADS: per-process C++ thread budget — set by the
    # shared-memory decode pool (data/workers.py) so N worker processes
    # x the solo default can't oversubscribe the host.
    env = os.environ.get("PDTT_NATIVE_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(8, (os.cpu_count() or 1) // 2))


def augment_batch(images: np.ndarray, pad: int, ys: np.ndarray, xs: np.ndarray,
                  flips: np.ndarray, mean: np.ndarray, std: np.ndarray,
                  nthreads: int = 0) -> np.ndarray:
    """Fused reflect-pad random crop + hflip + normalize.

    images: (B,H,W,C) uint8; ys/xs: (B,) offsets in [0, 2*pad];
    flips: (B,) bool. Returns (B,H,W,C) float32 = (x/255 - mean)/std.
    """
    B, H, W, C = images.shape
    out = np.empty((B, H, W, C), np.float32)
    _lib().imgops_augment_batch(
        np.ascontiguousarray(images), out, B, H, W, C, pad,
        np.ascontiguousarray(ys, np.int32),
        np.ascontiguousarray(xs, np.int32),
        np.ascontiguousarray(flips, np.uint8),
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        nthreads or default_threads(),
    )
    return out


def normalize_batch(images: np.ndarray, mean: np.ndarray, std: np.ndarray,
                    nthreads: int = 0) -> np.ndarray:
    """(B,H,W,C) uint8 → normalized float32."""
    B, H, W, C = images.shape
    out = np.empty((B, H, W, C), np.float32)
    _lib().imgops_normalize_batch(
        np.ascontiguousarray(images), out, B, H, W, C,
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        nthreads or default_threads(),
    )
    return out
