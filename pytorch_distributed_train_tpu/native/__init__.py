"""Native (C++) runtime components and their build machinery.

The reference's runtime layer is C++ where it matters (SURVEY §2.2: TCPStore
C5, DDP Reducer C7, DataLoader pin-memory C17, FlightRecorder C25). The TPU
stack obsoletes the Reducer (XLA schedules the collectives) but the
process-level runtime — rendezvous store, launcher plumbing, data-pipeline
hot loops — still wants native code. Sources live in ``<repo>/native/``;
each is compiled on demand into a shared library next to the source with
g++ (no pybind11 in the image — the C API + ctypes is the binding layer).
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import subprocess
import threading

_BUILD_LOCK = threading.Lock()


@contextlib.contextmanager
def _file_lock(path: str):
    """Advisory flock serializing builds ACROSS processes (tpurun spawns N
    workers that may all import the bindings on a fresh checkout)."""
    fd = os.open(path, os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def _native_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "native",
    )


def build_library(name: str, extra_flags: tuple[str, ...] = (),
                  extra_libs: tuple[str, ...] = ()) -> str:
    """Compile ``native/<name>.cpp`` → ``native/lib<name>.so`` if stale.

    Returns the .so path. Thread-safe; rebuilds only when the source is
    newer than the library (the make rule, inlined). ``extra_libs``
    (-l flags) go AFTER the source — ahead of it the linker discards them
    and the .so loads with undefined symbols.
    """
    src = os.path.join(_native_dir(), f"{name}.cpp")
    out = os.path.join(_native_dir(), f"lib{name}.so")
    with _BUILD_LOCK, _file_lock(out + ".lock"):
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        tmp = f"{out}.{os.getpid()}.tmp"  # per-pid: os.replace stays atomic
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
               *extra_flags, src, *extra_libs, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{e.stderr}"
            ) from e
        os.replace(tmp, out)
        return out
