"""ctypes bindings for the native JPEG batch decoder (native/jpegdec.cpp).

The decode arm of the input pipeline's native fast path (SURVEY C17 /
§7.4 hard part #1): Python reads raw JPEG bytes out of the tar shard and
owns the augmentation policy (crop boxes from its rng); the C++ side does
header parse, IDCT-scaled decode, crop-box bilinear resize, flip, and the
fused uint8→float32 normalize across a std::thread pool — no GIL.

``available()`` gates use: the build needs jpeglib.h + libjpeg; callers
fall back to the PIL per-item path when it's missing.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False


def _lib():
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        try:
            from pytorch_distributed_train_tpu.native import build_library

            lib = ctypes.CDLL(build_library("jpegdec", extra_libs=("-ljpeg",)))
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.jpegdec_dims.argtypes = [
                u8p, i64p, i64p, ctypes.c_int, i32p, ctypes.c_int]
            lib.jpegdec_dims.restype = ctypes.c_int
            lib.jpegdec_decode_batch.argtypes = [
                u8p, i64p, i64p, ctypes.c_int, f32p, u8p, ctypes.c_int,
                f32p, f32p, f32p, ctypes.c_int]
            lib.jpegdec_decode_batch.restype = ctypes.c_int
            _LIB = lib
        except (RuntimeError, OSError):
            _LIB = None
    return _LIB


def available() -> bool:
    return _lib() is not None


def default_threads() -> int:
    # PDTT_NATIVE_THREADS: per-process C++ thread budget — set by the
    # shared-memory decode pool (data/workers.py) so N worker processes
    # x the solo default can't oversubscribe the host.
    env = os.environ.get("PDTT_NATIVE_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(8, (os.cpu_count() or 1) // 2))


def _as_blob(blobs: list[bytes]):
    """Concatenate per-image byte strings → (blob, offsets, sizes)."""
    sizes = np.asarray([len(b) for b in blobs], np.int64)
    offs = np.zeros(len(blobs), np.int64)
    np.cumsum(sizes[:-1], out=offs[1:]) if len(blobs) > 1 else None
    blob = np.frombuffer(b"".join(blobs), np.uint8)
    return np.ascontiguousarray(blob), offs, sizes


def dims(blobs: list[bytes], nthreads: int = 0) -> np.ndarray:
    """(B, 2) int32 [width, height] per JPEG; [0, 0] on a corrupt header."""
    lib = _lib()
    assert lib is not None, "jpegdec library unavailable"
    blob, offs, sizes = _as_blob(blobs)
    out = np.zeros((len(blobs), 2), np.int32)
    lib.jpegdec_dims(blob, offs, sizes, len(blobs), out.reshape(-1),
                     nthreads or default_threads())
    return out


def decode_batch(blobs: list[bytes], boxes: np.ndarray, flips: np.ndarray,
                 size: int, mean: np.ndarray, std: np.ndarray,
                 nthreads: int = 0) -> tuple[np.ndarray, int]:
    """Decode + crop-resize + normalize a batch of JPEGs.

    boxes: (B, 4) float32 (x0, y0, w, h) in original pixel coords;
    flips: (B,) bool. Returns ((B, size, size, 3) float32, n_failures) —
    failed images are zeroed, matching the C side's poison-tolerance.
    """
    lib = _lib()
    assert lib is not None, "jpegdec library unavailable"
    blob, offs, sizes = _as_blob(blobs)
    boxes = np.ascontiguousarray(boxes, np.float32)
    flips_u8 = np.ascontiguousarray(flips, np.uint8)
    out = np.empty((len(blobs), size, size, 3), np.float32)
    fails = lib.jpegdec_decode_batch(
        blob, offs, sizes, len(blobs), boxes.reshape(-1), flips_u8, size,
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        out.reshape(-1), nthreads or default_threads())
    return out, int(fails)
