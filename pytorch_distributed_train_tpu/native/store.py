"""ctypes bindings for the native rendezvous store (native/store.cpp).

API mirrors c10d's TCPStore surface (set/get/add/wait —
torch:include/torch/csrc/distributed/c10d/TCPStore.hpp:73, Store.hpp): a
rank-0-hosted TCP KV server plus blocking clients. Used by the tpurun
launcher for gang rendezvous and restart barriers (SURVEY C5/C10/C11).
"""

from __future__ import annotations

import ctypes

from pytorch_distributed_train_tpu.native import build_library

_LIB: ctypes.CDLL | None = None


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        lib = ctypes.CDLL(build_library("store"))
        lib.tpustore_server_start.restype = ctypes.c_void_p
        lib.tpustore_server_start.argtypes = [ctypes.c_int]
        lib.tpustore_server_port.restype = ctypes.c_int
        lib.tpustore_server_port.argtypes = [ctypes.c_void_p]
        lib.tpustore_server_stop.argtypes = [ctypes.c_void_p]
        lib.tpustore_connect.restype = ctypes.c_void_p
        lib.tpustore_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int64]
        lib.tpustore_close.argtypes = [ctypes.c_void_p]
        lib.tpustore_set.restype = ctypes.c_int
        lib.tpustore_set.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.tpustore_get.restype = ctypes.c_int
        lib.tpustore_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int]
        lib.tpustore_add.restype = ctypes.c_int
        lib.tpustore_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64)]
        lib.tpustore_wait.restype = ctypes.c_int
        lib.tpustore_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.tpustore_del.restype = ctypes.c_int
        lib.tpustore_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpustore_numkeys.restype = ctypes.c_int
        lib.tpustore_numkeys.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
        _LIB = lib
    return _LIB


class StoreServer:
    """Hosts the KV store (launcher process / process 0). port=0 → ephemeral."""

    def __init__(self, port: int = 0):
        self._h = _lib().tpustore_server_start(port)
        if not self._h:
            raise OSError(f"tpustore: could not bind port {port}")
        self.port = _lib().tpustore_server_port(self._h)

    def stop(self) -> None:
        if self._h:
            _lib().tpustore_server_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class StoreClient:
    """Blocking client. All methods raise on transport errors; ``get``/
    ``wait`` raise TimeoutError when the key never appears."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_ms: int = 10_000):
        self._h = _lib().tpustore_connect(host.encode(), port, timeout_ms)
        if not self._h:
            raise ConnectionError(f"tpustore: cannot reach {host}:{port}")

    def set(self, key: str, value: bytes) -> None:
        if _lib().tpustore_set(self._h, key.encode(), value, len(value)) != 0:
            raise OSError(f"tpustore set({key!r}) failed")

    def get(self, key: str, timeout_ms: int = 60_000,
            max_len: int = 1 << 20) -> bytes:
        buf = ctypes.create_string_buffer(max_len)
        n = _lib().tpustore_get(self._h, key.encode(), timeout_ms, buf, max_len)
        if n == -2:
            raise TimeoutError(f"tpustore get({key!r}) timed out")
        if n < 0:
            raise OSError(f"tpustore get({key!r}) failed ({n})")
        return buf.raw[:n]

    def add(self, key: str, delta: int = 1) -> int:
        out = ctypes.c_int64(0)
        if _lib().tpustore_add(self._h, key.encode(), delta,
                               ctypes.byref(out)) != 0:
            raise OSError(f"tpustore add({key!r}) failed")
        return out.value

    def wait(self, key: str, timeout_ms: int = 60_000) -> None:
        r = _lib().tpustore_wait(self._h, key.encode(), timeout_ms)
        if r == -2:
            raise TimeoutError(f"tpustore wait({key!r}) timed out")
        if r != 0:
            raise OSError(f"tpustore wait({key!r}) failed")

    def delete(self, key: str) -> None:
        if _lib().tpustore_del(self._h, key.encode()) != 0:
            raise OSError(f"tpustore del({key!r}) failed")

    def num_keys(self) -> int:
        out = ctypes.c_int64(0)
        if _lib().tpustore_numkeys(self._h, ctypes.byref(out)) != 0:
            raise OSError("tpustore numkeys failed")
        return out.value

    def barrier(self, name: str, world: int, rank: int,
                timeout_ms: int = 60_000) -> None:
        """All ``world`` participants block until everyone arrives.

        The counter/flag two-phase pattern c10d uses for its store-based
        barrier; ``name`` must be unique per use (epoch it if reused).
        """
        n = self.add(f"barrier/{name}/count", 1)
        if n == world:
            self.set(f"barrier/{name}/go", b"1")
        self.wait(f"barrier/{name}/go", timeout_ms)

    def close(self) -> None:
        if self._h:
            _lib().tpustore_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
