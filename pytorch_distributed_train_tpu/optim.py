"""Optimizers + LR schedules as optax chains (SURVEY C20, H5).

Replaces torch.optim.SGD (torch:optim/sgd.py:28) and the reference's LAMB
(not in torch.optim — reference-era harnesses pull it from apex/local impl;
here it's optax.lamb, verified present in optax 0.2.6), plus
torch.optim.lr_scheduler (StepLR/CosineAnnealingLR) as optax schedules.

Gradient accumulation (`accum_steps>1`) wraps the chain in optax.MultiSteps —
the semantic equivalent of DDP's no_sync() microbatching (SURVEY C6): N
forward/backwards accumulate locally, collectives fire once per real step.
"""

from __future__ import annotations

import itertools
import re

import jax
import jax.numpy as jnp
import optax


def decay_mask_fn(exclude: str):
    """Weight-decay mask from comma-separated path regexes (OptimConfig.
    decay_exclude) — the torch-recipe "no_decay = ['bias', 'LayerNorm']"
    param-group split. Returns None (decay everything, torch's default)
    when no patterns are given; else a params-tree → bool-tree callable
    (True = apply decay) matching each '/'-joined param path."""
    patterns = [re.compile(p.strip()) for p in exclude.split(",") if p.strip()]
    if not patterns:
        return None

    def mask(params):
        from flax import traverse_util

        flat = traverse_util.flatten_dict(params)
        keep = {
            k: not any(p.search("/".join(map(str, k))) for p in patterns)
            for k in flat
        }
        return traverse_util.unflatten_dict(keep)

    return mask


_LAYER_PAT = re.compile(r"(?:^|/)(?:layer|layers_|stage|block)(\d+)")


def layer_lr_decay_transform(decay: float):
    """Layer-wise LR decay (the timm/BEiT/BERT fine-tune recipe): updates
    for depth-d params scale by decay^(D_max - d) — deeper (later) layers
    keep the full LR, the embedding/stem end trains slowest. Depth parses
    from the param path (layer<k>/layers_<k>/stage<k>); depthless params
    (embeddings, stem, final norm, head) split: head/final keep full LR,
    everything else gets the slowest rate, matching timm's grouping."""

    def scale_tree(params):
        from flax import traverse_util

        flat = traverse_util.flatten_dict(params)
        depths = {}
        for path in flat:
            m = _LAYER_PAT.search("/".join(map(str, path)))
            depths[path] = int(m.group(1)) if m else None
        known = [d for d in depths.values() if d is not None]
        if not known:
            raise ValueError(
                "layer_lr_decay found no depth-indexed params (expected "
                "layer<k>/layers_<k>/stage<k>/block<k> in the param paths) "
                "— it would silently become a uniform LR cut")
        d_max = max(known)
        out = {}
        for path, d in depths.items():
            name = "/".join(map(str, path))
            if d is None:
                tail = bool(re.search(
                    r"(head|fc|final_norm|classifier|logits)", name))
                d = d_max if tail else -1  # embeddings/stem: slowest
            out[path] = decay ** (d_max - d)
        return traverse_util.unflatten_dict(out)

    def init_fn(params):
        import jax.numpy as jnp

        return {"scales": jax.tree.map(jnp.float32, scale_tree(params))}


    def update_fn(updates, state, params=None):
        del params
        updates = jax.tree.map(lambda u, s: u * s, updates, state["scales"])
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


def make_schedule(opt_cfg, total_steps: int, steps_per_epoch: int = 0):
    """Learning-rate schedule with linear warmup.

    `step` schedule decays by `step_decay_rate` every `step_decay_every`
    EPOCHS (torch StepLR semantics, torch:optim/lr_scheduler.py:592) — needs
    steps_per_epoch; falls back to interpreting it as steps if unknown.
    """
    base = opt_cfg.learning_rate
    warmup = opt_cfg.warmup_steps
    decay_steps = max(total_steps - warmup, 1)

    if opt_cfg.schedule == "constant":
        main = optax.constant_schedule(base)
    elif opt_cfg.schedule == "cosine":
        main = optax.cosine_decay_schedule(
            base, decay_steps, alpha=opt_cfg.end_lr_factor
        )
    elif opt_cfg.schedule == "linear":
        main = optax.linear_schedule(base, base * opt_cfg.end_lr_factor, decay_steps)
    elif opt_cfg.schedule == "polynomial":
        # BERT-pretrain recipe (torch: LambdaLR with poly decay; HF
        # get_polynomial_decay_schedule_with_warmup): (1 - t/T)^power from
        # base LR down to end_lr_factor*base.
        main = optax.polynomial_schedule(
            init_value=base, end_value=base * opt_cfg.end_lr_factor,
            power=opt_cfg.poly_power, transition_steps=decay_steps)
    elif opt_cfg.schedule == "step":
        every = opt_cfg.step_decay_every * (steps_per_epoch or 1)
        boundaries_and_scales = {
            every * (i + 1): opt_cfg.step_decay_rate for i in range(100)
        }
        main = optax.piecewise_constant_schedule(base, boundaries_and_scales)
    elif opt_cfg.schedule == "onecycle":
        # torch OneCycleLR analogue. The policy owns its own ramp, so a
        # separate warmup would double-warm — reject the combination.
        if warmup > 0:
            raise ValueError(
                "schedule='onecycle' has a built-in warmup phase "
                "(onecycle_pct_start); set warmup_steps=0")
        return optax.cosine_onecycle_schedule(
            max(total_steps, 1), base,
            pct_start=opt_cfg.onecycle_pct_start,
        )
    elif opt_cfg.schedule == "cosine_restarts":
        # torch CosineAnnealingWarmRestarts: cycles of cosine decay back to
        # the base LR, each restart_mult times longer than the last. Same
        # domain rules as torch (T_mult >= 1, T_0 > 0) — shrinking cycles
        # would degenerate into ~horizon/1 one-step schedule closures.
        if opt_cfg.restart_mult < 1.0:
            raise ValueError(
                f"restart_mult must be >= 1, got {opt_cfg.restart_mult}")
        if opt_cfg.restart_period < 0:
            raise ValueError(
                f"restart_period must be >= 0, got {opt_cfg.restart_period}")
        period = opt_cfg.restart_period or max(decay_steps // 4, 1)
        periods: list[int] = []
        covered = 0
        while covered < decay_steps:
            periods.append(period)
            covered += period
            period = max(int(period * opt_cfg.restart_mult), 1)
        cycles = [optax.cosine_decay_schedule(base, p,
                                              alpha=opt_cfg.end_lr_factor)
                  for p in periods]
        boundaries = list(itertools.accumulate(periods))[:-1]
        main = optax.join_schedules(cycles, boundaries)
    else:
        raise ValueError(f"unknown schedule {opt_cfg.schedule!r}")

    if warmup > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, base, warmup), main], [warmup]
        )
    return main


def make_optimizer(opt_cfg, total_steps: int, steps_per_epoch: int = 0,
                   param_mask=None, sentinel_cooldown: bool = False):
    """Build the full optax transform chain.

    Order matters: clip → optimizer(+wd) → accumulate. Weight decay is
    decoupled (AdamW-style) for adamw/lamb and L2-coupled for SGD —
    matching torch's SGD(weight_decay=) semantics (torch:optim/sgd.py:252
    adds wd*p to the gradient before momentum).

    ``total_steps``/``steps_per_epoch`` are MICRO-steps (what the trainer
    counts); with accumulation the inner schedule advances once per
    ``accum_steps``, so horizons are converted to optimizer updates here.
    ``warmup_steps`` is therefore denominated in optimizer updates.

    ``sentinel_cooldown`` appends the sentinel's stateful LR-cooldown
    transform (sentinel/numeric.py) as the LAST chain element — like
    layer_lr_decay/plateau it scales FINAL updates, which is equivalent
    to scaling the LR. It stays 1.0 until an auto-rewind scales it down.
    """
    accum = max(opt_cfg.accum_steps, 1)
    sched = make_schedule(
        opt_cfg, max(1, total_steps // accum),
        max(1, steps_per_epoch // accum) if steps_per_epoch else 0,
    )
    swa_start = getattr(opt_cfg, "swa_start_step", 0)
    swa_lr = getattr(opt_cfg, "swa_lr", 0.0)
    if swa_start > 0 and swa_lr > 0.0:
        # SWALR (torch.optim.swa_utils.SWALR): hold a constant LR once
        # SWA collection starts — averaging wants iterates bouncing
        # around a flat region at fixed step size, not a decayed-to-zero
        # tail. Denominated in optimizer updates like warmup.
        base_sched = sched
        start_upd = max(swa_start, 1)  # already denominated in updates

        def sched(count):  # noqa: F811 — deliberate wrap
            return jnp.where(count >= start_upd, swa_lr, base_sched(count))
    parts = []
    # Comm-hook analogue (SURVEY C8): compression runs where the DDP hook
    # did — on the raw gradient, before clipping and the optimizer.
    hook = None
    if getattr(opt_cfg, "grad_hook", "none") not in ("", "none"):
        from pytorch_distributed_train_tpu import grad_hooks

        hook = grad_hooks.get_hook(
            opt_cfg.grad_hook, powersgd_rank=opt_cfg.powersgd_rank
        )
    if hook is not None:
        parts.append(hook)
    if opt_cfg.grad_clip_norm > 0:
        parts.append(optax.clip_by_global_norm(opt_cfg.grad_clip_norm))

    name = opt_cfg.name
    mask = decay_mask_fn(getattr(opt_cfg, "decay_exclude", ""))
    # Moment-storage dtype (OptimConfig.moment_dtype): optax casts mu to
    # this dtype between steps but computes the update in the grad dtype,
    # so numerics change only by the storage rounding. None → fp32.
    mu_dtype = getattr(opt_cfg, "moment_dtype", "") or None
    if name in ("sgd", "momentum"):
        if opt_cfg.weight_decay > 0:
            # torch-style coupled L2: grad += wd * param, then momentum.
            parts.append(
                optax.add_decayed_weights(opt_cfg.weight_decay, mask=mask))
        momentum = opt_cfg.momentum if name == "momentum" or opt_cfg.momentum else None
        parts.append(
            optax.sgd(sched, momentum=momentum, nesterov=opt_cfg.nesterov,
                      accumulator_dtype=mu_dtype if momentum else None)
        )
    elif name == "adam":
        if opt_cfg.weight_decay > 0:
            # torch.optim.Adam(weight_decay=) is coupled L2 (grad += wd*p),
            # unlike AdamW's decoupled decay.
            parts.append(
                optax.add_decayed_weights(opt_cfg.weight_decay, mask=mask))
        parts.append(optax.adam(sched, b1=opt_cfg.beta1, b2=opt_cfg.beta2,
                                eps=opt_cfg.eps, mu_dtype=mu_dtype))
    elif name == "adamw":
        parts.append(
            optax.adamw(sched, b1=opt_cfg.beta1, b2=opt_cfg.beta2,
                        eps=opt_cfg.eps, weight_decay=opt_cfg.weight_decay,
                        mask=mask, mu_dtype=mu_dtype)
        )
    elif name == "lion":
        # Lion (Chen et al. 2023, "Symbolic Discovery of Optimization
        # Algorithms"): sign(momentum-interpolated grad) updates — ONE
        # moment buffer (half adam's optimizer memory) and sign updates
        # that are bf16-friendly on TPU. Canonical recipe: lr ~3-10x
        # smaller and weight_decay ~3-10x larger than adamw's.
        # OptimConfig's beta2 default (0.999) is adam's; Lion's canonical
        # b2 is 0.99 — remap the untouched default so `optim.name=lion`
        # alone runs the published recipe (any other explicit value wins).
        lion_b2 = 0.99 if opt_cfg.beta2 == 0.999 else opt_cfg.beta2
        parts.append(
            optax.lion(sched, b1=opt_cfg.beta1, b2=lion_b2,
                       weight_decay=opt_cfg.weight_decay, mask=mask,
                       mu_dtype=mu_dtype)
        )
    elif name == "lamb":
        if mu_dtype is None:
            parts.append(
                optax.lamb(sched, b1=opt_cfg.beta1, b2=opt_cfg.beta2,
                           eps=opt_cfg.eps, weight_decay=opt_cfg.weight_decay,
                           mask=mask)
            )
        else:
            # optax.lamb doesn't expose mu_dtype; rebuild its documented
            # chain (scale_by_adam → decayed weights → trust ratio → lr)
            # with the narrowed first-moment storage.
            parts.append(optax.chain(
                optax.scale_by_adam(b1=opt_cfg.beta1, b2=opt_cfg.beta2,
                                    eps=opt_cfg.eps, mu_dtype=mu_dtype),
                optax.add_decayed_weights(opt_cfg.weight_decay, mask=mask),
                optax.scale_by_trust_ratio(),
                optax.scale_by_learning_rate(sched),
            ))
    elif name == "adafactor":
        # Memory-frugal LM optimizer (Shazeer & Stern 2018): second moments
        # factored into row+column statistics (O(n+m) per matrix instead of
        # O(n·m)), no first moment unless momentum is requested — the state
        # for a 7B model drops from ~2 params-worth (AdamW) to ~1%. The
        # external LR schedule is used as-is; parameter-scale multiplication
        # and update clipping follow the paper defaults.
        parts.append(optax.adafactor(
            sched,
            min_dim_size_to_factor=getattr(
                opt_cfg, "adafactor_min_dim_factored", 128),
            momentum=(getattr(opt_cfg, "adafactor_momentum", 0.0) or None),
            dtype_momentum=mu_dtype or "float32",
            weight_decay_rate=(opt_cfg.weight_decay
                               if opt_cfg.weight_decay > 0 else None),
            weight_decay_mask=mask if mask is not None else True,
        ))
    elif name == "muon":
        # Muon (Jordan et al. 2024, via optax.contrib): momentum
        # orthogonalized by Newton-Schulz iterations for matrix params,
        # AdamW for everything else. The NS iterations are five matmuls
        # per 2D param — MXU-native work, a natural TPU optimizer.
        # optax's default muon sends EVERY 2D param to the orthogonalized
        # branch — including embedding tables and the LM head, the params
        # the Muon recipe explicitly routes to adam. Partition ourselves:
        # embed/head params get a plain AdamW; everything else goes to
        # the default muon (which already handles its internal 2D-vs-rest
        # split). (Passing explicit MuonDimensionNumbers instead was
        # observed to under-orthogonalize in optax 0.2.6.)
        from optax import contrib as optax_contrib

        def muon_labels(params):
            from flax import traverse_util

            flat = traverse_util.flatten_dict(params)
            out = {
                path: ("adam" if re.search(
                    r"(embedding$|embed/|lm_head/|/head/|^head/)",
                    "/".join(map(str, path))) else "muon")
                for path in flat
            }
            return traverse_util.unflatten_dict(out)

        parts.append(optax.multi_transform(
            {
                "muon": optax_contrib.muon(
                    sched, beta=getattr(opt_cfg, "muon_beta", 0.95),
                    weight_decay=opt_cfg.weight_decay,
                    weight_decay_mask=mask if mask is not None else None,
                    mu_dtype=mu_dtype,
                    adam_b1=opt_cfg.beta1, adam_b2=opt_cfg.beta2),
                "adam": optax.adamw(
                    sched, b1=opt_cfg.beta1, b2=opt_cfg.beta2,
                    eps=opt_cfg.eps, weight_decay=opt_cfg.weight_decay,
                    mask=mask, mu_dtype=mu_dtype),
            },
            muon_labels,
        ))
    elif name == "schedule_free_adamw":
        # Schedule-Free AdamW (Defazio et al. 2024): no decay schedule at
        # all — the iterate interpolation replaces it. Training runs on
        # the z-sequence; EVALUATION must use schedule_free_eval_params
        # (trainer routes this via make_eval_step(schedule_free=True)).
        from optax import contrib as optax_contrib

        if opt_cfg.schedule not in ("constant",):
            raise ValueError(
                "schedule_free_adamw replaces the LR schedule by design — "
                "set schedule='constant' (warmup_steps is honored)")
        if getattr(opt_cfg, "plateau_factor", 0.0) > 0:
            raise ValueError(
                "schedule_free_adamw + plateau_factor: reduce_on_plateau "
                "would rescale the y-sequence updates out from under the "
                "ScheduleFreeState and is itself an LR schedule — "
                "disable one")
        if getattr(opt_cfg, "ema_decay", 0.0) > 0:
            raise ValueError(
                "schedule_free_adamw already averages iterates — EMA on "
                "top would evaluate the EMA of the z-sequence, which is "
                "neither; disable one")
        if mask is not None:
            raise ValueError(
                "schedule_free_adamw has no decay mask in optax — "
                "decay_exclude would be silently ignored; clear it or "
                "use adamw")
        if mu_dtype is not None:
            raise ValueError(
                "schedule_free_adamw does not narrow moment storage "
                "(optax state_dtype changes the z-iterate too) — clear "
                "moment_dtype or use adamw")
        parts.append(optax_contrib.schedule_free_adamw(
            learning_rate=opt_cfg.learning_rate,
            warmup_steps=opt_cfg.warmup_steps or None,
            b1=opt_cfg.beta1, b2=opt_cfg.beta2, eps=opt_cfg.eps,
            weight_decay=opt_cfg.weight_decay,
        ))
    elif name == "lars":
        # Large-batch ResNet recipe (MLPerf): layerwise trust ratio; the
        # no-decay params are also excluded from trust-ratio adaptation,
        # matching the reference implementations' skip of BN/bias.
        parts.append(
            optax.lars(sched, weight_decay=opt_cfg.weight_decay,
                       weight_decay_mask=mask if mask is not None else True,
                       trust_ratio_mask=mask if mask is not None else True,
                       momentum=opt_cfg.momentum, nesterov=opt_cfg.nesterov)
        )
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    if getattr(opt_cfg, "layer_lr_decay", 1.0) != 1.0:
        # Applied AFTER the optimizer (scales the final updates ≡ scaling
        # the LR per layer) — before it, adam's normalization would undo
        # the scaling.
        if not 0.0 < opt_cfg.layer_lr_decay <= 1.0:
            raise ValueError(
                f"layer_lr_decay must be in (0, 1], got "
                f"{opt_cfg.layer_lr_decay}")
        parts.append(layer_lr_decay_transform(opt_cfg.layer_lr_decay))
    if getattr(opt_cfg, "plateau_factor", 0.0) > 0.0:
        # torch ReduceLROnPlateau analogue: scales the UPDATES (≡ LR) down
        # by plateau_factor after plateau_patience updates without the
        # (plateau_accumulation-smoothed) loss improving. Appended after
        # the optimizer so it sees the final update magnitudes; the loss
        # reaches it as tx.update(..., value=loss) (train_state passes it
        # when the trainer enables plateau).
        from optax import contrib as optax_contrib

        parts.append(optax_contrib.reduce_on_plateau(
            factor=opt_cfg.plateau_factor,
            patience=opt_cfg.plateau_patience,
            cooldown=opt_cfg.plateau_cooldown,
            accumulation_size=max(opt_cfg.plateau_accumulation, 1),
            min_scale=opt_cfg.plateau_min_scale,
        ))
    if sentinel_cooldown:
        from pytorch_distributed_train_tpu.sentinel.numeric import (
            cooldown_transform,
        )

        parts.append(cooldown_transform())
    tx = optax.chain(*parts)
    if param_mask is not None:
        # LoRA-style trainable/frozen masking. Must wrap INSIDE MultiSteps:
        # train_state.py's accumulation-boundary detection (EMA gating,
        # plateau loss routing) keys on the TOP-LEVEL opt_state being a
        # MultiStepsState, which a mask wrapped outside would bury.
        tx = param_mask(tx)
    if opt_cfg.accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=opt_cfg.accum_steps)
    return tx, sched


def fused_update_unsupported_reason(opt_cfg, *, has_param_mask: bool = False
                                    ) -> str | None:
    """Why the fused one-pass epilogue (ops/fused_update.py) can NOT
    express this optimizer config — or None when it can.

    The fast path covers the chain shapes the presets actually run
    (clip → {adamw | adam | sgd/momentum} → sentinel cooldown, with
    decay masks and narrowed moment storage); everything else keeps the
    optax chain, which remains the reference oracle either way. A loud
    reason (not a silent fallback) is the repo convention: a knob that
    quietly does nothing records wrong measurements."""
    name = opt_cfg.name
    if name not in ("adamw", "adam", "sgd", "momentum"):
        return (f"optimizer {name!r} has no fused epilogue (supported: "
                "adamw/adam/sgd/momentum)")
    if getattr(opt_cfg, "grad_hook", "none") not in ("", "none"):
        return "grad_hook transforms run on the raw grads (unfusable here)"
    if getattr(opt_cfg, "layer_lr_decay", 1.0) != 1.0:
        return "layer_lr_decay adds a stateful per-depth scale link"
    if getattr(opt_cfg, "plateau_factor", 0.0) > 0.0:
        return "reduce_on_plateau is a stateful loss-driven link"
    if opt_cfg.accum_steps > 1:
        return ("optim.accum_steps wraps the chain in MultiSteps — use "
                "train.grad_accum_steps for in-graph accumulation instead")
    if has_param_mask:
        return "LoRA optimizer masking nests per-label inner states"
    return None


def make_fused_update(opt_cfg, sched, sentinel_cooldown: bool = False):
    """The make_optimizer FAST PATH: a FusedEpilogue whose one-pass
    update is numerically identical to the chain make_optimizer builds
    for the same (supported) config. ``sched`` must be the SAME
    schedule object make_optimizer returned — the two paths must read
    identical LRs at every count. Raises ValueError (with the reason)
    for configs the fast path cannot express."""
    reason = fused_update_unsupported_reason(opt_cfg)
    if reason is not None:
        raise ValueError(f"train.fused_epilogue: {reason}")
    from pytorch_distributed_train_tpu.ops.fused_update import FusedEpilogue

    name = opt_cfg.name
    momentum = None
    nesterov = False
    if name in ("sgd", "momentum"):
        momentum = (opt_cfg.momentum
                    if name == "momentum" or opt_cfg.momentum else None)
        nesterov = opt_cfg.nesterov
    mu_dtype = getattr(opt_cfg, "moment_dtype", "") or None
    if name in ("sgd", "momentum") and not momentum:
        # Mirror make_optimizer's TRUTHINESS check exactly
        # (`accumulator_dtype=mu_dtype if momentum else None`):
        # momentum=0.0 builds a TraceState but the chain keeps it fp32,
        # so the fused path must not narrow it either.
        mu_dtype = None
    return FusedEpilogue(
        kind="sgd" if name in ("sgd", "momentum") else name,
        sched=sched, b1=opt_cfg.beta1, b2=opt_cfg.beta2, eps=opt_cfg.eps,
        weight_decay=opt_cfg.weight_decay, momentum=momentum,
        nesterov=nesterov, clip_norm=opt_cfg.grad_clip_norm,
        cooldown=sentinel_cooldown, mu_dtype=mu_dtype,
        mask=decay_mask_fn(getattr(opt_cfg, "decay_exclude", "")),
    )


def schedule_free_eval(opt_state, params):
    """Schedule-Free evaluation params: locate the ScheduleFreeState in
    the (possibly chained/wrapped) optimizer state — duck-typed on its
    ``z`` iterate field — and interpolate. Passthrough when absent."""
    from optax import contrib as optax_contrib

    states = [s for s in jax.tree.leaves(
        opt_state, is_leaf=lambda s: hasattr(s, "z")) if hasattr(s, "z")]
    if not states:
        return params
    return optax_contrib.schedule_free_eval_params(states[0], params)


def plateau_scale(opt_state):
    """Current ReduceLROnPlateau LR scale from an optimizer state tree, or
    None when plateau isn't in the chain — the logging hook (the effective
    LR is schedule(step) * this)."""
    hits = []

    def visit(s):
        if hasattr(s, "plateau_count") and hasattr(s, "scale"):
            hits.append(s.scale)

    jax.tree.map(visit, opt_state,
                 is_leaf=lambda s: hasattr(s, "plateau_count"))
    return hits[0] if hits else None
