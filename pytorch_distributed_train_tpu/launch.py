"""Distributed bring-up (SURVEY L5, §3.1-3.2).

The reference needs torchrun + elastic agent + TCPStore rendezvous +
init_process_group('nccl') (SURVEY C5/C10: ~15k LoC of launcher machinery).
On TPU the pod is gang-scheduled and bootstrap is ONE call —
``jax.distributed.initialize`` starts/joins the coordination service
(coordinator = process 0), after which every process sees the global device
set. This module wraps that call with env-driven defaults so single-process
runs (the sandbox, CPU tests) skip it transparently.

Env contract (the torchrun RANK/WORLD_SIZE/MASTER_ADDR analogue — honored
when set, auto-detected on real TPU pods where libtpu supplies topology):
  COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID
"""

from __future__ import annotations

import os

import jax


def initialize_distributed(force: bool = False) -> None:
    """Idempotent jax.distributed.initialize with env-driven config.

    No-op for single-process runs unless env vars or `force` say otherwise —
    matching the reference's "CPU smoke config runs without DDP" behavior
    (BASELINE.json:7).
    """
    coord = os.environ.get("COORDINATOR_ADDRESS")
    nproc = os.environ.get("NUM_PROCESSES")
    pid = os.environ.get("PROCESS_ID")
    explicit = coord is not None or nproc is not None or pid is not None
    if not explicit and not force and not _on_multihost_tpu():
        return
    kwargs = {}
    if coord:
        kwargs["coordinator_address"] = coord
    if nproc:
        kwargs["num_processes"] = int(nproc)
    if pid:
        kwargs["process_id"] = int(pid)
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise


def _on_multihost_tpu() -> bool:
    # libtpu sets these on real pods. A single-entry TPU_WORKER_HOSTNAMES
    # (e.g. 'localhost' in the sandbox) is still a one-process job.
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h.strip()]) > 1:
        return True
    return bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def runtime_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }
