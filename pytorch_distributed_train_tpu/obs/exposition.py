"""Live ``/metrics`` exposition: the scrape surfaces over the registry.

Two deployment shapes, one renderer (registry.render):

- **Existing HTTP server** — tools/serve_http.py adds a ``GET /metrics``
  route that returns ``render_metrics()``; the serving process then
  exposes batcher counters, request histograms and span durations on the
  same port as the API.
- **Trainer sidecar** — a training process has no HTTP surface, so
  ``cfg.obs.metrics_port != 0`` starts ``MetricsServer``: a stdlib
  ThreadingHTTPServer on a daemon thread serving ``/metrics`` (and
  ``/healthz`` for liveness probes). Opt-in because a port bind is a
  side effect no test/bench run should pay by default. Port ``-1``
  binds an OS-assigned ephemeral port (tests, several trainers on one
  host) — read it back from ``server.port``.

The scrape handler never touches device state or locks shared with the
step loop: it reads plain-python counters, so a wedged train step can
still be scraped (exactly when you need the numbers most).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pytorch_distributed_train_tpu.obs.registry import get_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def render_metrics() -> str:
    """The exposition body — shared by every scrape surface. Memory
    telemetry (obs/memory.py) refreshes first, so host/device headroom
    gauges are scrape-fresh on every surface (trainer sidecar AND
    serve_http) without any per-process sampling loop."""
    try:
        from pytorch_distributed_train_tpu.obs import memory as memory_lib

        memory_lib.sample_memory_gauges()
    except Exception:
        pass  # telemetry must never break the scrape
    return get_registry().render()


# ``POST /profile`` hook: the trainer registers a callback that opens a
# managed profiler capture (obs/profiler.py) — the sidecar is often the
# ONLY reachable surface of a misbehaving remote run, which is exactly
# when an on-demand capture is wanted. The callback may return a
# CaptureRequest (step-windowed), a capture-dir string (time-bounded),
# or None (a window is already open).
_PROFILE_TRIGGER = None
_TRIGGER_LOCK = threading.Lock()


def set_profile_trigger(fn) -> None:
    """Install (or clear, with None) the capture-request callback."""
    global _PROFILE_TRIGGER
    with _TRIGGER_LOCK:
        _PROFILE_TRIGGER = fn


def clear_profile_trigger(fn) -> None:
    """Clear the callback ONLY if ``fn`` is still the installed one — a
    closing Trainer must not detach a newer Trainer's sidecar route."""
    global _PROFILE_TRIGGER
    with _TRIGGER_LOCK:
        if _PROFILE_TRIGGER is fn:
            _PROFILE_TRIGGER = None


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet: scrapes are periodic
        pass

    def do_GET(self):
        if self.path.split("?", 1)[0] == "/metrics":
            body = render_metrics().encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
        elif self.path == "/healthz":
            body = b'{"status": "ok"}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        else:
            body = b"not found\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        if self.path.split("?", 1)[0] != "/profile":
            body, code = b"not found\n", 404
        else:
            with _TRIGGER_LOCK:
                fn = _PROFILE_TRIGGER
            if fn is None:
                body, code = (b'{"error": "no profiler attached"}\n', 503)
            else:
                try:
                    req = fn()
                    if req is None:
                        body = b'{"error": "capture already open"}\n'
                        code = 409
                    elif isinstance(req, str):  # time-bounded: the dir
                        body = (json.dumps({"status": "capturing",
                                            "dir": req}).encode() + b"\n")
                        code = 202
                    else:
                        body = (json.dumps(
                            {"status": "requested",
                             "reason": getattr(req, "reason", "http"),
                             "start_step": getattr(req, "start_step", None),
                             "window": getattr(req, "window", None)})
                            .encode() + b"\n")
                        code = 202
                except Exception as e:  # the scrape surface must survive
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    code = 500
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsServer:
    """Opt-in scrape sidecar for processes without an HTTP surface.

    ``port <= 0`` binds an OS-assigned ephemeral port (both -1, the
    config sentinel, and a literal 0 land here — the "off" meaning of
    ``cfg.obs.metrics_port == 0`` is the caller's gate, not this
    class's). A fixed port that is already bound raises OSError
    (EADDRINUSE) to the caller: the trainer's policy is to fall back to
    ephemeral and publish the ACTUAL port through the store endpoint
    record, so a second worker on the same host never crashes on the
    shared config value (docs/observability.md).
    """

    def __init__(self, port: int, host: str = "0.0.0.0"):
        self._httpd = ThreadingHTTPServer((host, max(port, 0)), _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-exposition")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
