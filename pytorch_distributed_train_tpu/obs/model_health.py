"""Host-side model-health monitor: divergence early warning BEFORE the
loss moves.

The in-graph half (ops/model_health.py, gated by ``obs.model_health``)
lands training-dynamics scalars — grad/param/update norms, update-to-
param ratios — in the step metrics; the GRPO/rollout path adds reward,
advantage, entropy and KL-to-behavior series. This module is the host
half: a ``ModelHealthMonitor`` holding one sentinel ``SpikeDetector``
per watched series (the same healthy-only median+MAD windows the loss
sentinel uses — sentinel/numeric.py), fed once per log cadence from the
already-transferred host record, so it adds zero device syncs.

Why a separate monitor when the sentinel already watches the loss: the
loss is a LAGGING indicator. A per-block gradient explosion or an
update that suddenly dwarfs its weights shows up steps before the loss
diverges; reward collapse and KL runaway show up before an online
policy degrades visibly. Catching the precursor means the rewind
replays a couple of steps instead of a couple hundred, and the
profiler can capture the step window where the dynamics actually
broke.

Verdicts are journaled under the CLOSED ``model`` event category
(obs/events.py) with the optimizer-scale context that makes them
actionable post-hoc (lr, loss_scale, lr_cooldown_scale at the moment
of the warning), counted per series, and fed to the managed profiler's
anomaly hook (obs/profiler.py: journal always, capture when
``profile_on_anomaly``). A warning streak across consecutive
observations ARMS the sentinel rewind — the trainer treats an armed
monitor exactly like a sentinel bad-step streak.

No jax at module scope (the obs/ package contract).
"""

from __future__ import annotations

import statistics

from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.sentinel.numeric import SpikeDetector

# series -> unhealthy direction. "above": only an upward deviation is a
# warning (a gradient norm FALLING is news, not danger); "below" the
# mirror (reward/entropy collapse). Deviations in the healthy direction
# still enter the window — they ARE the new baseline.
WATCHED: dict[str, str] = {
    "grad_norm": "above",
    "update_norm": "above",
    "update_ratio_max": "above",
    "kl_behavior": "above",
    "reward_mean": "below",
    "token_entropy": "below",
}

# optimizer-scale context stamped onto every warning record: the
# post-mortem question is always "what was the LR/scale doing there"
_CONTEXT_KEYS = ("lr", "loss_scale", "lr_cooldown_scale")


class ModelHealthMonitor:
    """Per-series spike detection over the host-side metrics record.

    ``observe(step, record)`` returns True when the warning streak has
    crossed ``arm_streak`` — the caller's cue to trigger the sentinel
    rewind path. Detector windows are healthy-only (a warning value
    never contaminates its own baseline) and ``reset()`` after a rewind
    forgets the pre-rewind regime, same stance as the loss sentinel.
    """

    def __init__(self, *, window: int = 64, sigma: float = 6.0,
                 min_samples: int = 8, min_rel: float = 0.5,
                 arm_streak: int = 3, profiler=None,
                 watch: dict[str, str] | None = None):
        self.watch = dict(WATCHED if watch is None else watch)
        self.profiler = profiler
        self.arm_streak = max(1, int(arm_streak))
        self._streak = 0
        self._detectors = {
            name: SpikeDetector(window=window, sigma=sigma,
                                min_samples=min_samples, min_rel=min_rel)
            for name in self.watch}

    # ------------------------------------------------------------ verdicts
    def _directed(self, name: str, value: float, det: SpikeDetector) -> bool:
        """Spike AND in the unhealthy direction for this series."""
        if not det.is_spike(value):
            return False
        med = statistics.median(det.window)
        direction = self.watch[name]
        return value > med if direction == "above" else value < med

    def observe(self, step: int, record: dict) -> bool:
        """Feed one host metrics record (the ``_log_train`` dict).

        Absent series are skipped (an image run has no ``kl_behavior``;
        a run without ``model_health`` never feeds ``update_ratio_max``)
        — the monitor watches whatever telemetry actually flows.
        Returns True when the rewind should be armed.
        """
        context = {k: record[k] for k in _CONTEXT_KEYS if k in record}
        warned = []
        for name, det in self._detectors.items():
            raw = record.get(name)
            if raw is None or isinstance(raw, bool):
                continue
            try:
                value = float(raw)
            except (TypeError, ValueError):
                continue
            if value != value:  # NaN: the numeric guard's territory
                continue
            if self._directed(name, value, det):
                warned.append(name)
                baseline = statistics.median(det.window)
                get_registry().counter(
                    "model_health_warnings_total",
                    labels={"series": name},
                    help="model-health divergence early warnings by "
                         "series").inc()
                events_lib.emit(
                    "model", "early_warning", step=step, series=name,
                    value=round(value, 6), baseline=round(baseline, 6),
                    direction=self.watch[name], streak=self._streak + 1,
                    **context)
            else:
                det.add(value)
        self._streak = self._streak + 1 if warned else 0
        get_registry().gauge(
            "model_health_warning_streak",
            help="consecutive observations with >=1 model-health "
                 "warning").set(self._streak)
        if warned and self.profiler is not None:
            # journal always; opens a capture window on the step where
            # the dynamics broke when obs.profile_on_anomaly is set
            self.profiler.anomaly("model_health", step,
                                  series=",".join(warned),
                                  streak=self._streak)
        if self._streak >= self.arm_streak:
            get_registry().counter(
                "model_health_rewinds_armed_total",
                help="rewind triggers armed by the model-health "
                     "monitor").inc()
            events_lib.emit("model", "rewind_armed", step=step,
                            series=",".join(warned), streak=self._streak,
                            **context)
            return True
        return False

    def reset(self) -> None:
        """Forget every window (post-rewind: the replayed region's
        telemetry re-enters from scratch)."""
        self._streak = 0
        get_registry().gauge(
            "model_health_warning_streak",
            help="consecutive observations with >=1 model-health "
                 "warning").set(0)
        for det in self._detectors.values():
            det.reset()
