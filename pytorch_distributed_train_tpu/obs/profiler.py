"""Managed profiler plane: bounded capture windows, opened ON TRIGGER.

The legacy profiler window (``obs.profile_start_step`` /
``obs.profile_num_steps``) is a fixed manual aperture: the operator
guesses a step before launch, and the window is never open at the
moment an anomaly actually fires. This plane makes the profiler a
managed resource instead:

- **bounded windows** — every capture is N steps (``jax.profiler``
  start/stop around the step loop) into its own artifact directory
  under ``obs.profile_dir``, auto-summarized through the
  utils/xplane.py top-ops report and journaled (obs/events.py).
- **triggers** — a capture can be requested
    * on cadence (``obs.profile_every_steps``),
    * on demand: a trigger FILE (touch ``<run>/PROFILE``) or the
      metrics sidecar's ``POST /profile`` route (obs/exposition.py) /
      tools/serve_http.py's ``POST /profile``,
    * cross-host-coordinated: under tpurun the request is published on
      the launcher worker_store and every host captures the SAME step
      window (a one-host profile of a collective stall blames the
      wrong thing),
    * automatically by anomaly hooks: sentinel loss-spike, cross-host
      straggler blame, and a rolling median+MAD step-time /
      input-stall regression detector (sentinel/numeric.py math) —
      gated by ``obs.profile_on_anomaly`` + a cooldown so a bad hour
      can't fill the disk.
- **retention** — completed captures form a ring
  (``obs.profile_ring``): oldest ``capture_*`` directories are evicted
  once the ring is full, so triggered profiling can run unattended.

The backend is injectable (``backend=``): tests drive every trigger
path deterministically on the CPU mesh with a fake capture object; the
default lazily wraps ``jax.profiler`` (no jax at module scope — the
obs/ package contract).

The legacy window keeps working as a shim: ``profile_num_steps > 0``
pre-queues one capture at ``profile_start_step`` writing directly into
``obs.profile_dir`` (old output layout, exempt from the ring).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import shutil
import threading
import time

from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.sentinel.numeric import SpikeDetector

# launcher-store key all hosts poll for coordinated capture requests
REQUEST_KEY = "profiler/request"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class JaxProfilerBackend:
    """The real thing: ``jax.profiler`` trace sessions."""

    def start(self, logdir: str) -> None:
        import jax

        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)

    def stop(self) -> None:
        import jax

        jax.profiler.stop_trace()


@dataclasses.dataclass
class CaptureRequest:
    """One requested window. ``start_step`` -1 = start immediately
    (time-bounded ad-hoc captures from HTTP surfaces)."""

    id: str
    reason: str
    start_step: int
    window: int
    logdir: str = ""  # "" → ring-managed capture_* dir
    in_ring: bool = True

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, raw: str) -> "CaptureRequest":
        d = json.loads(raw)
        return cls(**{f.name: d[f.name]
                      for f in dataclasses.fields(cls) if f.name in d})


def straggler_blame(summary: dict, ratio: float) -> int | None:
    """Pure trigger predicate over the cluster aggregate
    (obs/cluster.py summarize output): the max host is BLAMED when its
    step-time p50 exceeds ``ratio`` x the cluster median. Returns the
    blamed host id or None; 0 disables."""
    if not ratio:
        return None
    med = summary.get("step_time_p50_med")
    mx = summary.get("step_time_p50_max")
    if med is None or mx is None or med <= 0:
        return None
    if mx >= ratio * med:
        return int(summary.get("step_time_p50_max_host", -1))
    return None


class ManagedProfiler:
    """Step-loop-driven capture state machine + trigger plumbing.

    The trainer calls ``on_step(step)`` once per loop iteration (cheap
    when dormant: one attr check, one stat) and feeds the anomaly
    detectors (``observe_step_time`` / ``observe_stall_pct``); every
    other surface funnels into ``request_capture``.
    """

    def __init__(self, obs_cfg, run_dir: str, *, backend=None,
                 store_factory=None, rank: int | None = None,
                 world: int | None = None):
        self.cfg = obs_cfg
        self.run_dir = run_dir
        self.backend = backend if backend is not None else JaxProfilerBackend()
        self.rank = rank if rank is not None else _env_int("PROCESS_ID", 0)
        self.world = world if world is not None else _env_int(
            "NUM_PROCESSES", 1)
        self.profile_dir = obs_cfg.profile_dir or os.path.join(
            run_dir, "profiles")
        self.trigger_file = obs_cfg.profile_trigger_file or os.path.join(
            run_dir, "PROFILE")
        self.window = max(1, int(getattr(obs_cfg, "profile_window_steps", 5)))
        self._lock = threading.Lock()
        self._pending: CaptureRequest | None = None
        self._active = None  # (request, started_step, logdir, t0)
        self._step = 0
        self._req_n = 0
        self._seen_req_id: str | None = None
        self._last_auto_step: int | None = None
        self._stop = threading.Event()
        self._watch_thread: threading.Thread | None = None
        self._timer: threading.Timer | None = None
        self._factory = store_factory
        # median+MAD regression detectors (the sentinel loss-spike math
        # pointed at wall-clock health): step time per step, input-stall
        # % per log window. Healthy-only windows, same rationale.
        self._dt_det = SpikeDetector(
            window=getattr(obs_cfg, "profile_regress_window", 64),
            sigma=getattr(obs_cfg, "profile_regress_sigma", 8.0),
            min_samples=getattr(obs_cfg, "profile_regress_min_samples", 16),
            min_rel=getattr(obs_cfg, "profile_regress_min_rel", 0.5))
        self._stall_det = SpikeDetector(
            window=getattr(obs_cfg, "profile_regress_window", 64),
            sigma=getattr(obs_cfg, "profile_regress_sigma", 8.0),
            min_samples=max(
                4, getattr(obs_cfg, "profile_regress_min_samples", 16) // 4),
            min_rel=getattr(obs_cfg, "profile_regress_min_rel", 0.5))

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Arm the plane: queue the legacy-window shim and (under a
        launcher store) start the coordinated-request watcher."""
        if getattr(self.cfg, "profile_num_steps", 0) > 0:
            # Legacy obs.profile_* shim: same window, same output root
            # (no capture_* subdir, never ring-evicted).
            self._adopt(CaptureRequest(
                id=self._new_id("legacy"), reason="legacy",
                start_step=int(self.cfg.profile_start_step),
                window=int(self.cfg.profile_num_steps),
                logdir=self.profile_dir, in_ring=False))
        store = self._open_store()
        if store is None:
            return
        try:  # a stale request from a previous life must not re-fire
            self._seen_req_id = CaptureRequest.from_json(
                store.get(REQUEST_KEY, timeout_ms=1).decode()).id
        except Exception:
            self._seen_req_id = None
        self._watch_thread = threading.Thread(
            target=self._watch, args=(store,), daemon=True,
            name="profiler-request-watch")
        self._watch_thread.start()

    def finish(self, step: int | None = None) -> None:
        """Close an open window (fit() ending mid-capture) and stop the
        watcher. Idempotent."""
        self._stop.set()
        if self._timer is not None:
            self._timer.cancel()
        with self._lock:
            active = self._active is not None
        if active:
            self._stop_capture(self._step if step is None else step)
        t = self._watch_thread
        if t is not None:
            t.join(timeout=2.0)
            self._watch_thread = None

    # -------------------------------------------------------------- store
    def _open_store(self):
        """A ResilientStore over the configured factory (store_plane:
        bounded ops + retry + health scoring), or None when no store
        is configured — a store-less run must not feed the health
        machine phantom failures from a 5 Hz watcher. The probe client
        is handed to the wrapper as its first connection, not closed
        and re-dialed."""
        factory = self._factory
        if factory is None:
            from pytorch_distributed_train_tpu.elastic import worker_store

            factory = worker_store
        try:
            probe = factory()
        except Exception:
            return None
        if probe is None:
            return None
        first = [probe]

        def _fac():
            if first:
                return first.pop()
            return factory()

        from pytorch_distributed_train_tpu import store_plane

        return store_plane.ResilientStore(_fac, name="profiler")

    def _watch(self, store) -> None:
        """Poll the launcher store for coordinated capture requests —
        every host (including the requester) adopts the same window."""
        try:
            while not self._stop.wait(0.2):
                try:
                    raw = store.get(REQUEST_KEY, timeout_ms=1)
                except TimeoutError:
                    continue  # no request published yet
                except OSError:
                    continue  # store degraded: ResilientStore scored
                    # it; keep watching — the outage ends, we resume
                try:
                    req = CaptureRequest.from_json(raw.decode())
                except (ValueError, TypeError, KeyError):
                    continue
                if req.id == self._seen_req_id:
                    continue
                self._seen_req_id = req.id
                self._adopt(req)
        except Exception:
            pass  # store gone (teardown): the plane goes dark
        finally:
            try:
                store.close()
            except Exception:
                pass

    # ----------------------------------------------------------- requests
    def _new_id(self, reason: str) -> str:
        self._req_n += 1
        return f"{self.rank}-{self._req_n}-{reason}"

    def request_capture(self, reason: str, *, start_step: int | None = None,
                        window: int | None = None,
                        coordinate: bool = True) -> CaptureRequest:
        """Request one window. With a launcher store and
        ``coordinate=True`` the request is PUBLISHED so every host
        captures the same steps; otherwise it is adopted locally.
        ``start_step`` defaults a couple of steps ahead so remote hosts
        have time to adopt before the window opens."""
        if start_step is None:
            start_step = self._step + 2
        req = CaptureRequest(
            id=self._new_id(reason), reason=reason,
            start_step=int(start_step),
            window=int(window or self.window))
        store = self._open_store() if coordinate else None
        if store is not None:
            try:
                store.set(REQUEST_KEY, req.to_json().encode())
            except Exception:
                self._adopt(req)  # store flaked: capture locally at least
            finally:
                try:
                    store.close()
                except Exception:
                    pass
        else:
            self._adopt(req)
        return req

    def _adopt(self, req: CaptureRequest) -> None:
        with self._lock:
            if self._active is not None or self._pending is not None:
                return  # one window at a time; overlapping asks collapse
            self._pending = req

    # ---------------------------------------------------------- step loop
    def on_step(self, step: int) -> None:
        """Drive the window state machine at a step boundary."""
        self._step = step
        with self._lock:
            active, pending = self._active, self._pending
        if active is not None:
            req, started, _, _ = active
            # ad-hoc (time-bounded) windows are owned by their timer,
            # not the step counter — start_step -1 marks them
            if req.start_step >= 0 and step >= started + req.window:
                self._stop_capture(step)
            return
        if os.path.exists(self.trigger_file):
            try:
                os.remove(self.trigger_file)
            except OSError:
                pass  # another host on a shared FS won the race
            else:
                # No explicit start_step: the default few-step lead is
                # what lets REMOTE hosts adopt the store-published
                # request before the window opens, so all hosts capture
                # the same steps.
                self.request_capture("trigger_file")
                with self._lock:
                    pending = self._pending
        every = getattr(self.cfg, "profile_every_steps", 0)
        if pending is None and every and step > 0 and step % every == 0:
            # cadence: every host computes the same boundary — aligned
            # by construction, no store round-trip needed
            self.request_capture("cadence", start_step=step,
                                 coordinate=False)
            with self._lock:
                pending = self._pending
        if pending is not None and step >= pending.start_step:
            self._start_capture(pending, step)

    # ----------------------------------------------------------- anomalies
    def observe_step_time(self, dt_s: float, step: int) -> None:
        """Feed one meter tick to the step-time regression detector."""
        with self._lock:
            if self._active is not None:
                return  # profiler overhead must not poison the baseline
        if self._dt_det.is_spike(dt_s):
            self.anomaly("step_time_regression", step,
                         dt_ms=round(dt_s * 1e3, 3))
            # Re-baseline: unlike the sentinel loss detector (whose
            # streak is bounded by the rewind), nothing recovers a
            # PERSISTENT step-time shift — without a reset it would
            # journal one anomaly per step forever. A fresh window
            # adopts the new regime within min_samples ticks and
            # bounds the event rate to ~1 per min_samples steps.
            self._dt_det.reset()
        else:
            self._dt_det.add(dt_s)

    def observe_stall_pct(self, pct: float, step: int) -> None:
        """Feed one log window's input-stall %% to its detector. An
        absolute floor (``profile_stall_min_pct``) keeps a near-zero
        baseline from flagging the first nonzero wait as a regression."""
        floor = getattr(self.cfg, "profile_stall_min_pct", 5.0)
        if pct >= floor and self._stall_det.is_spike(pct):
            self.anomaly("input_stall_regression", step,
                         stall_pct=round(pct, 3))
            self._stall_det.reset()  # same re-baseline as step time
        else:
            self._stall_det.add(pct)

    def anomaly(self, kind: str, step: int, **detail) -> None:
        """An anomaly fired: journal it always; open a capture when
        ``profile_on_anomaly`` and outside the auto-capture cooldown."""
        events_lib.emit("anomaly", kind, step=step, **detail)
        get_registry().counter(
            "profiler_anomalies_total", labels={"kind": kind},
            help="anomaly-detector firings seen by the profiler "
                 "plane").inc()
        if not getattr(self.cfg, "profile_on_anomaly", False):
            return
        with self._lock:
            if self._active is not None or self._pending is not None:
                # a window is already in flight: the request would be
                # collapsed anyway — don't burn the cooldown on it
                return
        cooldown = getattr(self.cfg, "profile_cooldown_steps", 200)
        if (self._last_auto_step is not None
                and step - self._last_auto_step < cooldown):
            return
        self._last_auto_step = step
        self.request_capture(kind, start_step=step + 1)

    # ------------------------------------------------------- capture core
    def _capture_dir(self, req: CaptureRequest) -> str:
        if req.logdir:
            return req.logdir
        if req.start_step >= 0:
            # deterministic across hosts: every host's window lands in
            # the same directory (jax writes per-host files inside)
            name = f"capture_step{req.start_step:08d}_{req.reason}"
        else:
            name = f"capture_adhoc_{req.reason}_{req.id}"
        return os.path.join(self.profile_dir, name)

    def _start_capture(self, req: CaptureRequest, step: int) -> bool:
        """Claim-then-start: the window slot is taken under the lock
        BEFORE the backend call, so concurrent openers (step loop vs a
        POST /profile handler thread) cannot double-start the backend
        or cross-wire each other's stop timers."""
        logdir = self._capture_dir(req)
        with self._lock:
            if self._pending is req:
                self._pending = None
            if self._active is not None:
                return False  # lost the race: one window at a time
            self._active = (req, step, logdir, time.perf_counter())
        try:
            self.backend.start(logdir)
        except Exception as e:
            get_registry().counter(
                "profiler_errors_total",
                help="capture start/stop failures (backend)").inc()
            print(f"[profiler] capture start failed "
                  f"({type(e).__name__}: {e}); dropping request "
                  f"{req.reason}", flush=True)
            with self._lock:
                self._active = None
            return False
        get_registry().counter(
            "profiler_captures_total", labels={"trigger": req.reason},
            help="managed profiler captures by trigger").inc()
        events_lib.emit("profile", "capture_start", step=step,
                        reason=req.reason, dir=logdir, window=req.window)
        print(f"[profiler] capture open at step {step} "
              f"({req.reason}, {req.window} steps) -> {logdir}", flush=True)
        return True

    def _stop_capture(self, step: int, only: CaptureRequest | None = None
                      ) -> None:
        """Close the open window. ``only`` restricts the stop to THAT
        request's window — a stale ad-hoc timer must not kill a capture
        someone else opened after its own ended."""
        with self._lock:
            if self._active is None:
                return
            if only is not None and self._active[0] is not only:
                return
            req, started, logdir, t0 = self._active
            self._active = None
        try:
            self.backend.stop()
        except Exception as e:
            get_registry().counter(
                "profiler_errors_total",
                help="capture start/stop failures (backend)").inc()
            print(f"[profiler] capture stop failed "
                  f"({type(e).__name__}: {e})", flush=True)
        summary = self._summarize(logdir)
        events_lib.emit(
            "profile", "capture_end", step=step, reason=req.reason,
            dir=logdir, steps=step - started,
            wall_s=round(time.perf_counter() - t0, 3),
            summary=summary.splitlines()[:12])
        # Perf attribution (obs/perf.py): op-class split + gauges + one
        # `perf` journal record per capture. Best-effort inside — an
        # environment without the xplane proto still keeps the capture.
        from pytorch_distributed_train_tpu.obs import perf as perf_lib

        mfu = get_registry().get_value("perf_mfu_pct")
        perf_lib.attribute_capture(
            logdir, step=step, mfu_pct=mfu,
            top=getattr(self.cfg, "profile_top_ops", 5))
        print(f"[profiler] capture closed at step {step} ({req.reason}); "
              f"summary:\n{summary}", flush=True)
        if req.in_ring:
            self._gc_ring()

    def _summarize(self, logdir: str) -> str:
        """Best-effort top-ops report over the fresh dump — the capture
        is useful without it (the xplane proto needs the tsl protobuf)."""
        try:
            from pytorch_distributed_train_tpu.utils import xplane

            text = xplane.report(
                logdir, top=getattr(self.cfg, "profile_top_ops", 5))
        except Exception as e:
            text = (f"(xplane summary unavailable: "
                    f"{type(e).__name__}: {e})")
        try:
            with open(os.path.join(logdir, "top_ops.txt"), "w") as f:
                f.write(text + "\n")
        except OSError:
            pass
        return text

    def _gc_ring(self) -> None:
        """Keep the newest ``profile_ring`` completed capture dirs."""
        keep = max(1, int(getattr(self.cfg, "profile_ring", 4)))
        dirs = [d for d in glob.glob(
            os.path.join(self.profile_dir, "capture_*"))
            if os.path.isdir(d)]
        dirs.sort(key=lambda d: os.path.getmtime(d), reverse=True)
        for d in dirs[keep:]:
            shutil.rmtree(d, ignore_errors=True)
            get_registry().counter(
                "profiler_ring_evicted_total",
                help="capture directories evicted by ring "
                     "retention").inc()
            events_lib.emit("profile", "ring_evict",
                            dir=os.path.basename(d))

    # ------------------------------------------------------- ad-hoc (HTTP)
    def capture_for_seconds(self, seconds: float,
                            reason: str = "http") -> str | None:
        """Time-bounded capture for step-less surfaces (the serving
        process, a wedged-looking trainer poked over the sidecar).
        Returns the capture dir, or None when a window is already
        open. The stop timer is bound to THIS request (``only=``) so
        concurrent callers can't truncate each other's windows."""
        req = CaptureRequest(id=self._new_id(reason), reason=reason,
                             start_step=-1, window=0)
        if not self._start_capture(req, self._step):
            return None
        self._timer = threading.Timer(
            max(0.05, float(seconds)), self._stop_capture,
            args=(self._step,), kwargs={"only": req})
        self._timer.daemon = True
        self._timer.start()
        return self._capture_dir(req)
