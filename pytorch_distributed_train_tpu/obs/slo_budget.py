"""Declarative SLO catalog + error-budget accounting over the tsdb.

The alert catalog (obs/alerts.py) answers "is something anomalous
RIGHT NOW". This module answers the operator's slower question — "are
we keeping our promises" — with the three pieces Google-SRE burn-rate
alerting needs, all computed from the durable per-target history in
``obs/tsdb.TimeSeriesStore``:

- **SLO_CATALOG** — the CLOSED set of service-level objectives
  (serving availability, TTFT p95, trainer goodput, steps/s floor).
  Each SLO names the collector series that is its SLI, the good-side
  threshold, the objective (target good fraction) and the budget
  window. Mirrored in docs/observability.md's '## SLO catalog' table
  and cross-checked both ways by the ``slo-catalog`` pass of
  ``python -m tools.analyze`` — the fault-points/event-categories/
  alert-rules pattern, applied a fifth time.
- **SLI semantics** — a scrape sample is GOOD when its value sits on
  the SLO's good side of the threshold; the SLI over a window is the
  good fraction of its samples. Sample-based (not request-based) on
  purpose: it is computable for trainer series where "a request" does
  not exist, and the collector's scrape cadence makes samples a fair
  proxy for time.
- **burn rates & budgets** — ``burn_rate(slo, target, window)`` =
  bad_fraction(window) / (1 - objective): 1.0 means "spending the
  budget exactly as fast as the SLO allows", N means N× too fast.
  ``budget_remaining(slo, target)`` over the SLO's own window is the
  fraction of error budget left (negative = overspent).

The multi-window multi-burn-rate RULES themselves (fast 5m/1h page +
slow 30m/6h warn per SLO) are declared in obs/alerts.py ``RULES``
(kind ``burn_rate``) so they ride the existing engine lifecycle —
firing→resolved transitions journaled under ``alert``, counted,
cooldown-limited — and this module only does the math. A rule fires
when BOTH its windows burn over the factor (the short window proves
it is happening now, the long window proves it is not a blip) and
resolves as soon as either recovers.

``export_gauges`` mirrors the accounting into the metric catalog:
``slo_error_budget_remaining{slo=}`` (worst target) and
``slo_burn_rate{slo=,window=}`` (worst target's fast/slow actionable
burn — the min of each pair, since both windows must agree to act).

Stdlib + obs.tsdb/registry only; no jax (login-host safe).
"""

from __future__ import annotations

import dataclasses
import time

from pytorch_distributed_train_tpu.obs.registry import get_registry


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declared objective. ``series`` is the collector series the
    SLI reads; a sample is good when its value is ``good`` (below /
    above) the ``threshold``; ``objective`` is the target good
    fraction over ``window_s``."""

    name: str
    roles: tuple                   # ("serving",) / ("trainer",)
    series: str
    good: str                      # "below" | "above"
    threshold: float
    objective: float               # target good fraction, in (0, 1)
    window_s: float                # error-budget accounting window
    description: str


GOOD_SIDES = ("below", "above")

# The CLOSED catalog — docs/observability.md '## SLO catalog' mirrors
# this table; tools/analyze's slo-catalog pass keeps the two in sync.
SLO_CATALOG: dict[str, SLO] = {s.name: s for s in (
    SLO(name="serve_availability", roles=("serving",),
        series="shed_per_s", good="below", threshold=1.0,
        objective=0.99, window_s=3600.0,
        description="admission availability: a scrape sample is good "
                    "when the replica sheds under 1 req/s (429s are "
                    "the error budget, not an outage)"),
    SLO(name="serve_ttft_p95", roles=("serving",),
        series="ttft_p95_s", good="below", threshold=0.5,
        objective=0.95, window_s=3600.0,
        description="latency: windowed TTFT p95 under 500ms — the "
                    "promise the whole serving plane defends"),
    SLO(name="trainer_goodput", roles=("trainer",),
        series="goodput_pct", good="above", threshold=50.0,
        objective=0.95, window_s=3600.0,
        description="trainer goodput above 50%% productive — restarts "
                    "and stalls spend this budget"),
    SLO(name="trainer_steps_floor", roles=("trainer",),
        series="steps_per_s", good="above", threshold=0.1,
        objective=0.90, window_s=3600.0,
        description="throughput floor: steps/s above 0.1 — a slower "
                    "fleet is a budget spend, a stopped one an alert"),
)}

# (short_s, long_s) per burn window; factor = burn-rate threshold.
# The classic SRE pairs: the fast pair pages (a real, current fire),
# the slow pair warns (a sustained slow leak).
BURN_WINDOWS: dict[str, tuple[float, float]] = {
    "fast": (300.0, 3600.0),
    "slow": (1800.0, 21600.0),
}
BURN_FACTORS: dict[str, float] = {"fast": 14.4, "slow": 3.0}


class SLOBudgetTracker:
    """Error-budget accounting over a TimeSeriesStore.

    Target keys are the collector's history keys (``role@host``), so
    role scoping falls out of the key prefix. Every method returns
    None when the store holds no samples for the window — an SLO with
    no evidence is unknown, not violated (the never-scraped blame
    rule, budget-flavored)."""

    def __init__(self, store, catalog: dict | None = None,
                 clock=time.time):
        self.store = store
        self.catalog = dict(catalog if catalog is not None
                            else SLO_CATALOG)
        self.clock = clock

    # ------------------------------------------------------------- math
    def _bad_fraction(self, slo: SLO, target_key: str,
                      window_s: float, now: float) -> float | None:
        pts = self.store.query(target_key, slo.series,
                               now - window_s, now)
        if not pts:
            return None
        if slo.good == "below":
            bad = sum(1 for _ts, v in pts if v > slo.threshold)
        else:
            bad = sum(1 for _ts, v in pts if v < slo.threshold)
        return bad / len(pts)

    def burn_rate(self, slo_name: str, target_key: str,
                  window_s: float, now: float | None = None
                  ) -> float | None:
        slo = self.catalog[slo_name]
        now = self.clock() if now is None else now
        bf = self._bad_fraction(slo, target_key, window_s, now)
        if bf is None:
            return None
        return bf / max(1e-9, 1.0 - slo.objective)

    def budget_remaining(self, slo_name: str, target_key: str,
                         now: float | None = None) -> float | None:
        """Fraction of the error budget left over the SLO's own
        window; 1.0 = untouched, 0.0 = spent, negative = overspent."""
        slo = self.catalog[slo_name]
        now = self.clock() if now is None else now
        bf = self._bad_fraction(slo, target_key, slo.window_s, now)
        if bf is None:
            return None
        return 1.0 - bf / max(1e-9, 1.0 - slo.objective)

    # ---------------------------------------------------------- rollups
    def _targets_for(self, slo: SLO) -> list[str]:
        return [t for t in self.store.targets()
                if t.partition("@")[0] in slo.roles]

    def status(self, now: float | None = None) -> dict:
        """Per-SLO rollup the console panel and obs_report render:
        worst-target budget remaining + per-window burn rates (the
        actionable burn of each pair: min(short, long), worst across
        targets)."""
        now = self.clock() if now is None else now
        out: dict[str, dict] = {}
        for name, slo in self.catalog.items():
            targets: dict[str, dict] = {}
            for key in self._targets_for(slo):
                rem = self.budget_remaining(name, key, now)
                if rem is None:
                    continue
                burns = {}
                for win, (short_s, long_s) in BURN_WINDOWS.items():
                    sb = self.burn_rate(name, key, short_s, now)
                    lb = self.burn_rate(name, key, long_s, now)
                    if sb is not None and lb is not None:
                        burns[win] = min(sb, lb)
                targets[key] = {"budget_remaining": rem, "burn": burns}
            if not targets:
                continue
            worst_key = min(targets,
                            key=lambda k: targets[k]["budget_remaining"])
            rollup_burn = {
                win: max((t["burn"][win] for t in targets.values()
                          if win in t["burn"]), default=None)
                for win in BURN_WINDOWS}
            worst_win = None
            numeric = {w: b for w, b in rollup_burn.items()
                       if b is not None}
            if numeric:
                worst_win = max(numeric, key=numeric.get)
            out[name] = {
                "budget_remaining":
                    targets[worst_key]["budget_remaining"],
                "worst_target": worst_key,
                "burn": rollup_burn,
                "worst_window": worst_win,
                "objective": slo.objective,
                "window_s": slo.window_s,
                "targets": targets,
            }
        return out

    def export_gauges(self, now: float | None = None) -> None:
        reg = get_registry()
        for name, st in self.status(now).items():
            reg.gauge("slo_error_budget_remaining",
                      labels={"slo": name},
                      help="fraction of SLO error budget left over the "
                           "budget window (worst target; negative = "
                           "overspent)").set(st["budget_remaining"])
            for win, burn in st["burn"].items():
                if burn is None:
                    continue
                reg.gauge("slo_burn_rate",
                          labels={"slo": name, "window": win},
                          help="actionable SLO burn rate per window "
                               "pair (min of short/long, worst target; "
                               "1.0 = spending exactly at the SLO "
                               "rate)").set(burn)
