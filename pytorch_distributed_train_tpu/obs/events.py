"""Append-only structured event journal: one JSONL file per host.

Every subsystem that changes the SHAPE of a run — fault injections,
sentinel skips/rewinds, checkpoint tier traffic, elastic restarts,
preemptions, profiler captures — already prints a log line, but prose
logs from N hosts across M restart generations cannot be merged back
into "what happened to this run" without archaeology. This journal is
the machine-readable spine those subsystems emit into instead: one
record per event, append-only, per-host files that a post-mortem tool
(tools/timeline_report.py) merges into a single cross-host timeline.

Record schema (one JSON object per line)::

    {ts, step, host, gen, category, name, detail}

    ts       — epoch seconds (time.time; the same clock the span
               recorder anchors to, so journals and traces align)
    step     — trainer step counter, or null for steps-less contexts
               (the elastic agent, serving tools)
    host     — writer identity: "host<rank>" for workers (PROCESS_ID),
               "agent<node>" for the launcher
    gen      — RESTART_GENERATION at write time (journals append across
               restarts; gen is what separates the lives)
    category — one of CATEGORIES below (validated: a typo'd category is
               a silent fault, same stance as faults/registry.py)
    name     — event name within the category (e.g. "rewind")
    detail   — free-form JSON-serializable kwargs from the emitter
    trace    — OPTIONAL: the distributed-trace id (obs/tracing.py) —
               from the emitting thread's trace scope, or passed
               explicitly as ``emit(..., trace=...)`` by off-scope
               emitters — so journal records cross-link with retained
               trace trees

Categories are a CLOSED catalog, cross-checked against the table in
docs/observability.md by tools/check_events.py (the check_fault_points
pattern): an event stream readers can't interpret is noise.

Thread model: emitters run on the step loop, persister thread, liveness
watcher and HTTP handlers; one lock serializes the write+flush pair.
Every emit also increments ``obs_events_total{category=}`` whether or
not a sink is configured, so scrape dashboards see event rates even
when nobody journals to disk. Journaling is best-effort: a full disk
must degrade the post-mortem, never the run.

No jax at module scope (the obs/ package contract): the elastic agent
and data workers journal without touching a device backend.
"""

from __future__ import annotations

import json
import os
import threading
import time

from pytorch_distributed_train_tpu.obs import spans as spans_lib

# category -> one-line meaning (the docs/observability.md table mirrors
# this; tools/check_events.py keeps the two in sync both ways)
CATEGORIES: dict[str, str] = {
    "lifecycle": "process milestones: trainer init, fit start/end",
    "fault": "injected fault fires (faults/registry.py)",
    "sentinel": "numeric/liveness verdicts: bad steps, rewinds, hangs",
    "ckpt": "checkpoint traffic: saves, persists, restores by tier",
    "elastic": "launcher: spawns, worker failures, gang restarts",
    "preempt": "graceful preemption markers",
    "anomaly": "detector firings: loss spikes, stragglers, regressions",
    "profile": "managed profiler captures and their summaries",
    "serve": "request-path reliability: sheds, deadline expiries, slot "
             "leaks, drains, router failovers and hedges",
    "perf": "performance attribution: per-capture MFU/op-class splits "
            "and perf-ledger rows (obs/perf.py)",
    "alert": "fleet alert-rule transitions: fired, resolved, capture "
             "requests (obs/alerts.py)",
    "action": "fleet-controller decisions and their lifecycle: "
              "requested, acting, effective, failed, rolled_back, "
              "skipped, mode latches (fleet/controller.py)",
    "sanitizer": "runtime concurrency-sanitizer findings: lock-order "
                 "inversions, hold-while-blocking, unjoined threads, "
                 "deadlock watchdog trips (utils/syncdbg.py)",
    "store": "launcher-store resilience plane: health transitions "
             "(degraded/down/recovered), liveness blame suspensions "
             "during store outages (store_plane.py, "
             "sentinel/liveness.py)",
    "weights": "online post-training plane: weight publishes, replica "
               "swaps (applied/rejected), rollout batches "
               "(online/, tools/serve_http.py)",
    "model": "model-health early warnings: training-dynamics spikes "
             "(grad/update norms, update ratios), reward/KL drift "
             "verdicts, rewind arming (obs/model_health.py)",
}


class EventJournal:
    """One writer, one append-only JSONL file (lazily opened)."""

    def __init__(self, dir_path: str | None = None, who: str | None = None,
                 gen: str | None = None):
        self.dir = dir_path
        self.who = who if who is not None else (
            f"host{os.environ.get('PROCESS_ID', '0')}")
        self.gen = gen if gen is not None else os.environ.get(
            "RESTART_GENERATION", "0")
        self._lock = threading.Lock()
        self._fh = None
        self._failed = False  # print the sink failure once, then drop

    @property
    def path(self) -> str | None:
        if not self.dir:
            return None
        return os.path.join(self.dir, f"events_{self.who}.jsonl")

    def emit(self, category: str, name: str, step: int | None = None,
             trace: str | None = None, **detail) -> None:
        if category not in CATEGORIES:
            raise KeyError(
                f"unknown event category {category!r} "
                f"(catalog: {sorted(CATEGORIES)})")
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        get_registry().counter(
            "obs_events_total", labels={"category": category},
            help="journaled structured events by category").inc()
        if not self.dir or self._failed:
            return
        rec = {"ts": time.time(),
               "step": None if step is None else int(step),
               "host": self.who, "gen": self.gen,
               "category": category, "name": name, "detail": detail}
        # correlation: an event emitted inside an active trace scope —
        # or handed an explicit ``trace=`` id (scheduler threads have
        # no scope) — carries the trace id top-level, so journal
        # records and retained trace trees cross-link
        # (docs/observability.md tracing section)
        if trace is None:
            tr = spans_lib.current_trace()
            trace = tr[0] if tr is not None else None
        if trace is not None:
            rec["trace"] = trace
        try:
            line = json.dumps(rec, default=repr)
        except (TypeError, ValueError):
            rec["detail"] = {"unserializable": repr(detail)}
            line = json.dumps(rec)
        with self._lock:
            try:
                if self._fh is None:
                    os.makedirs(self.dir, exist_ok=True)
                    self._fh = open(self.path, "a")
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError as e:
                self._failed = True
                print(f"[events] journal sink failed ({e}); further "
                      "events counted but not persisted", flush=True)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# ------------------------------------------------------------ process-global
_GLOBAL: EventJournal | None = None
_LOCK = threading.Lock()

ENV_VAR = "PDTT_EVENTS_DIR"


def configure(dir_path: str | None, who: str | None = None,
              gen: str | None = None) -> EventJournal:
    """Install the process-global journal. ``dir_path`` None means
    metrics-only (events counted, nothing persisted). Reconfiguring
    closes the previous sink (several Trainers per test process)."""
    global _GLOBAL
    j = EventJournal(dir_path, who=who, gen=gen)
    with _LOCK:
        prev, _GLOBAL = _GLOBAL, j
    if prev is not None:
        prev.close()
    return j


def get_journal() -> EventJournal:
    """The process-global journal; lazily built from PDTT_EVENTS_DIR
    alone when nothing configured one (elastic agent, data workers,
    serving tools)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _LOCK:
            if _GLOBAL is None:
                _GLOBAL = EventJournal(os.environ.get(ENV_VAR) or None)
    return _GLOBAL


def emit(category: str, name: str, step: int | None = None,
         trace: str | None = None, **detail) -> None:
    """``emit("sentinel", "rewind", step=6, to=4)`` against the global
    journal — the one-liner call sites use. ``trace=`` overrides the
    thread-scope trace-id stamp (for emitters running off-scope, like
    the serving scheduler)."""
    get_journal().emit(category, name, step=step, trace=trace, **detail)


def load_events(dir_path: str) -> list[dict]:
    """Read every ``events_*.jsonl`` under ``dir_path``, merged and
    ts-sorted (the timeline/report tools' loader). Torn tail lines of a
    crashed writer are skipped."""
    import glob

    recs: list[dict] = []
    for path in sorted(glob.glob(os.path.join(dir_path, "events_*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "category" in rec:
                        recs.append(rec)
        except OSError:
            continue
    recs.sort(key=lambda r: r.get("ts", 0.0))
    return recs


def _reset_for_tests() -> None:
    global _GLOBAL
    with _LOCK:
        prev, _GLOBAL = _GLOBAL, None
    if prev is not None:
        prev.close()
