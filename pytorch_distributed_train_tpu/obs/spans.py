"""Host-side trace spans: a ring buffer of timed regions + Chrome export.

The device side of "where does wall-clock go" is already covered by the
xplane profiler window (utils/xplane.py); what was missing is the HOST
side — compile vs step vs input stall vs checkpoint vs eval vs request
handling. ``span("checkpoint.save")`` costs two ``perf_counter`` calls
and one ring slot, cheap enough for per-step use; the ring holds the
last ``capacity`` completed spans so the watchdog can dump "what was the
host doing" on abort (utils/watchdog.py attaches the recorder next to
the FlightRecorder event ring).

Export is the Chrome ``trace.json`` array format (``ph: "X"`` complete
events, microsecond timestamps) — load it in chrome://tracing or
Perfetto alongside the xplane-derived device trace; both clocks are
host epoch-anchored so the two align (docs/observability.md).

Thread model: completed spans append under the GIL (list assignment into
a preallocated ring is atomic enough, same design as FlightRecorder);
the nesting stack is thread-local so producer threads and HTTP handler
threads nest independently. Each span records its thread name — the
Chrome export maps it to ``tid`` rows.

Distributed tracing (obs/tracing.py, docs/observability.md): inside a
``trace_scope`` every completed span additionally carries
``trace_id`` / ``span_id`` / ``parent_id`` — the Dapper-style causal
identity a request keeps across router → replica → batcher hops — and
is forwarded to the registered trace sink (the tail-based sampler).
Spans outside a scope pay nothing new. Process-wide *correlation tags*
(``set_correlation_tags``: the trainer's (gen, step), a replica's
weight version) ride every span as a separate ``corr`` dict so the
cross-process merge can line serving traces up against what the
co-resident trainer was doing.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

# ---------------------------------------------------------- trace context
# The active (trace_id, parent_span_id) of the calling thread, None when
# untraced. obs/tracing.py owns the wire format + sampling; this module
# only stamps ids so the hot span path stays import-light.
_TL_TRACE = threading.local()

# Process-wide correlation tags stamped (as Span.corr, NOT merged into
# args) on every completed span: {"gen": ..., "step": ...} from the
# trainer, {"weight_version": ...} from a serving replica.
_CORR: dict = {}

# Completed spans carrying a trace_id are handed here (obs/tracing.py
# registers the tail sampler at import). Kept as a late-bound global so
# spans.py never imports tracing.
_TRACE_SINK = None


def _rand_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def current_trace() -> tuple[str, str | None] | None:
    """The calling thread's (trace_id, open span id) or None. The second
    element is what an outbound hop / explicit ``record()`` call should
    parent to."""
    return getattr(_TL_TRACE, "ctx", None)


@contextlib.contextmanager
def trace_scope(trace_id: str, parent_id: str | None):
    """Install a trace context on the calling thread: spans opened inside
    get real trace/span/parent ids (nested spans parent to each other)."""
    prev = getattr(_TL_TRACE, "ctx", None)
    _TL_TRACE.ctx = (trace_id, parent_id)
    try:
        yield
    finally:
        _TL_TRACE.ctx = prev


def set_correlation_tags(**tags) -> None:
    """Merge process-wide correlation tags stamped on every span
    (``None`` value removes a tag). The trainer sets ``gen``/``step`` at
    step cadence; serving sets ``weight_version`` — ROADMAP-4's weight
    swap updates it and becomes traceable day one."""
    for k, v in tags.items():
        if v is None:
            _CORR.pop(k, None)
        else:
            _CORR[k] = v


def correlation_tags() -> dict:
    return dict(_CORR)


def set_trace_sink(fn) -> None:
    global _TRACE_SINK
    _TRACE_SINK = fn


class Span:
    """One completed timed region."""

    __slots__ = ("name", "t0", "dur_s", "thread", "depth", "args",
                 "trace_id", "span_id", "parent_id", "corr")

    def __init__(self, name: str, t0: float, dur_s: float, thread: str,
                 depth: int, args: dict, trace_id: str | None = None,
                 span_id: str | None = None, parent_id: str | None = None,
                 corr: dict | None = None):
        self.name = name
        self.t0 = t0  # epoch seconds (time.time clock)
        self.dur_s = dur_s
        self.thread = thread
        self.depth = depth
        self.args = args
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.corr = corr

    def to_chrome(self, pid: int) -> dict:
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": self.t0 * 1e6,  # Chrome wants microseconds
            "dur": self.dur_s * 1e6,
            "pid": pid,
            "tid": self.thread,
        }
        args = dict(self.corr) if self.corr else {}
        args.update(self.args or {})
        if self.trace_id is not None:
            args["trace_id"] = self.trace_id
            args["span_id"] = self.span_id
            if self.parent_id is not None:
                args["parent_id"] = self.parent_id
        if args:
            ev["args"] = args
        return ev


class SpanRecorder:
    """Fixed-capacity ring of completed spans + thread-local nest stacks."""

    def __init__(self, capacity: int = 4096, feed_registry: bool = True):
        self.capacity = capacity
        self.buf: list[Span | None] = [None] * capacity
        self.n = 0  # total spans ever completed
        self._local = threading.local()
        self._feed_registry = feed_registry
        # slot-claim + n++ is a read-modify-write pair; concurrent
        # completions (producer thread vs step loop vs HTTP handlers)
        # could otherwise double-write a slot and leave a None hole
        # that crashes chrome_trace. Held for two assignments only.
        self._commit_lock = threading.Lock()
        # thread-name -> that thread's open-span stack. The stack is
        # only MUTATED by its own thread; the dict gives other threads
        # (watchdog abort dump) read access the pure thread-local
        # couldn't — a wedged main-thread checkpoint.save must be
        # visible from the heartbeat thread.
        self._stacks: dict[str, list] = {}

    # ------------------------------------------------------------- record
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
            self._stacks[threading.current_thread().name] = st
        return st

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """Time a region. Nesting is tracked per thread (``depth``);
        exceptions propagate — the span still records, flagged
        ``error=True`` so an aborted checkpoint save is visible in the
        dump. Under an active ``trace_scope`` the span gets trace ids
        and becomes the parent of spans nested inside it."""
        stack = self._stack()
        stack.append(name)
        tr = getattr(_TL_TRACE, "ctx", None)
        trace_id = span_id = parent_id = None
        if tr is not None:
            trace_id, parent_id = tr
            span_id = _rand_id(8)
            _TL_TRACE.ctx = (trace_id, span_id)
        wall0 = time.time()
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            args = {**args, "error": True}
            raise
        finally:
            if tr is not None:
                _TL_TRACE.ctx = tr
            dur = time.perf_counter() - t0
            depth = len(stack) - 1
            stack.pop()
            sp = Span(name, wall0, dur, threading.current_thread().name,
                      depth, args, trace_id=trace_id, span_id=span_id,
                      parent_id=parent_id,
                      corr=dict(_CORR) if _CORR else None)
            self._commit(sp)

    def record(self, name: str, t0_wall: float, dur_s: float, *,
               trace: tuple[str, str | None] | None = None,
               thread: str | None = None, **args) -> str | None:
        """Commit a span with EXPLICIT timing — for phases measured by a
        different thread than the one that owns them (the serving
        scheduler records each request's queue / prefill / per-quantum
        decode spans from the step loop). ``trace`` is
        ``(trace_id, parent_span_id)``; None reads the calling thread's
        active scope. Returns the new span id (None when untraced)."""
        if trace is None:
            trace = current_trace()
        trace_id = parent_id = span_id = None
        if trace is not None:
            trace_id, parent_id = trace
            span_id = _rand_id(8)
        sp = Span(name, t0_wall, dur_s,
                  thread or threading.current_thread().name, 0, args,
                  trace_id=trace_id, span_id=span_id, parent_id=parent_id,
                  corr=dict(_CORR) if _CORR else None)
        self._commit(sp)
        return span_id

    def _commit(self, sp: Span) -> None:
        with self._commit_lock:
            self.buf[self.n % self.capacity] = sp
            self.n += 1
        if sp.trace_id is not None and _TRACE_SINK is not None:
            _TRACE_SINK(sp)
        if self._feed_registry:
            # every span is scrape-visible as a labeled histogram —
            # the decode-wait / ckpt-time numbers come for free
            from pytorch_distributed_train_tpu.obs.registry import (
                get_registry,
            )

            get_registry().histogram(
                "span_seconds", labels={"name": sp.name},
                help="duration of host trace spans by span name",
            ).observe(sp.dur_s)

    # -------------------------------------------------------------- read
    def events(self) -> list[Span]:
        """Completed spans, oldest first (ring order). None-filtered: a
        reader racing an in-flight commit may see a not-yet-filled slot."""
        if self.n <= self.capacity:
            snap = self.buf[: self.n]
        else:
            i = self.n % self.capacity
            snap = self.buf[i:] + self.buf[:i]
        return [s for s in snap if s is not None]

    def active(self) -> list[str]:
        """This thread's currently-open span names, outermost first."""
        return list(self._stack())

    def active_all(self) -> dict[str, list[str]]:
        """EVERY thread's open spans (non-empty stacks only) — the abort
        dump runs on the heartbeat thread, where ``active()`` is vacuous."""
        return {t: list(st) for t, st in list(self._stacks.items()) if st}

    def clear(self) -> None:
        self.buf = [None] * self.capacity
        self.n = 0

    # ------------------------------------------------------------ export
    def chrome_trace(self) -> dict:
        pid = os.getpid()
        return {
            "traceEvents": [s.to_chrome(pid) for s in self.events()],
            "displayTimeUnit": "ms",
        }

    def dump_chrome_trace(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def write_text(self, out) -> None:
        """Human dump (watchdog abort path): last spans, one per line."""
        evs = self.events()
        out.write(f"=== trace spans: last {len(evs)} "
                  f"(of {self.n} total) ===\n")
        for s in evs:
            out.write(f"{s.t0:.3f} {'  ' * s.depth}{s.name} "
                      f"{s.dur_s * 1e3:.2f}ms thread={s.thread} {s.args}\n")
        out.flush()


_GLOBAL: SpanRecorder | None = None
_GLOBAL_LOCK = threading.Lock()


def get_recorder() -> SpanRecorder:
    """The process-wide recorder: trainer, checkpoint, data producers and
    HTTP handlers all record into one ring, so the exported trace shows
    their interleaving on a single timeline."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = SpanRecorder()
    return _GLOBAL


def span(name: str, **args):
    """``with span("trainer.eval"): ...`` against the global recorder."""
    return get_recorder().span(name, **args)
