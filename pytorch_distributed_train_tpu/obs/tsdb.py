"""Embedded per-target time-series store: the fleet's durable memory.

PR 13's collector keeps every series in a bounded in-memory deque —
observable in the moment, amnesiac past the window, gone with the
process. This module is the history half: a dependency-free store the
collector writes THROUGH on every scrape, so "shed storm for 10
minutes" and "TTFT budget 80%% burned this hour" are answerable
questions (obs/slo_budget.py asks them) and a console restart loses
nothing.

Layout (one directory tree, shared-storage friendly):

    <root>/<role>@<host>/<series>/<tier>/open.jsonl
    <root>/<role>@<host>/<series>/<tier>/chunk-<start_ms>.tsc

- **open.jsonl** — the append-only ACTIVE chunk: one JSON row per
  sample, flushed per append, so a SIGKILLed collector loses at most
  the OS page cache (nothing, for a process kill). A torn tail line is
  skipped on read — it is the routine shape of a kill, not corruption.
- **chunk-*.tsc** — a SEALED chunk: written to ``.tmp`` and published
  by ``os.replace`` (the packed_cache/manifest atomic-seal pattern),
  magic + JSON header + packed little-endian float64 payload with the
  payload's CRC32 in the header. A truncated or bit-flipped sealed
  chunk fails its CRC on read, is IGNORED, and counts into
  ``tsdb_chunk_corrupt_total`` — a reader never crashes on a torn
  file and never silently serves garbage.
- **tiers** — ``raw`` (every scrape sample, rows ``[ts, value]``) plus
  downsampled aggregates maintained ONLINE as raw samples arrive
  (default 10s and 1m buckets, rows ``[bucket_ts, min, max, mean,
  last, count]``): long-range queries read a few aggregate rows
  instead of re-scanning every scrape ever taken.

Retention is a DISK budget, not an age: ``gc()`` (run after every
seal) evicts the oldest sealed chunks until the store fits, but never
a chunk a still-open query iterator holds pinned, and never the
newest sealed chunk of any (target, series, tier) — history shrinks
from the far end only, and an in-flight read never has its data
deleted out from under it.

Timestamps are WALL-CLOCK epoch seconds (the caller stamps them):
history must survive process restarts and be joinable against the
event journal, which monotonic time cannot do.

Stdlib only; no jax anywhere near this module (obs/ package contract
— it runs on a login host).
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import zlib

from pytorch_distributed_train_tpu.obs.registry import get_registry

MAGIC = b"PDTTTSC1"
RAW = "raw"
AGGS = ("min", "max", "mean", "last", "count", "sum")
_SAN = re.compile(r"[^A-Za-z0-9_.@-]+")


def _safe(name: str) -> str:
    return _SAN.sub("_", str(name)) or "_"


def _tier_name(width_s: float) -> str:
    return f"{int(width_s)}s"


def _chunk_name(start_ts: float) -> str:
    return f"chunk-{int(start_ts * 1000):015d}.tsc"


def write_chunk(path: str, series: str, tier: str,
                rows: list[tuple]) -> None:
    """Seal ``rows`` (each a tuple of floats, all the same width) into
    one immutable chunk: tmp + fsync-less atomic rename, CRC of the
    payload in the header. Readers see the old state or the new state,
    never a half-written file."""
    cols = len(rows[0])
    payload = b"".join(struct.pack(f"<{cols}d", *r) for r in rows)
    header = {
        "series": series, "tier": tier, "n": len(rows), "cols": cols,
        "start": rows[0][0], "end": rows[-1][0],
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }
    hbytes = json.dumps(header, sort_keys=True).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", len(hbytes)))
        f.write(hbytes)
        f.write(payload)
    os.replace(tmp, path)


def read_chunk(path: str) -> tuple[dict, list[tuple]] | None:
    """(header, rows) of a sealed chunk — or None (counted into
    ``tsdb_chunk_corrupt_total``) when the file is torn, truncated or
    fails its CRC. A corrupt chunk is a hole in history, not a crash."""
    try:
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ValueError("bad magic")
            (hlen,) = struct.unpack("<I", f.read(4))
            header = json.loads(f.read(hlen).decode("utf-8"))
            cols, n = int(header["cols"]), int(header["n"])
            payload = f.read(cols * n * 8)
            if len(payload) != cols * n * 8:
                raise ValueError("truncated payload")
            if (zlib.crc32(payload) & 0xFFFFFFFF) != header["crc32"]:
                raise ValueError("crc mismatch")
        rows = [struct.unpack_from(f"<{cols}d", payload, i * cols * 8)
                for i in range(n)]
        return header, rows
    except (OSError, ValueError, KeyError, struct.error):
        get_registry().counter(
            "tsdb_chunk_corrupt_total",
            help="sealed tsdb chunks ignored for torn/truncated/CRC "
                 "failure").inc()
        return None


class _Bucket:
    """Online aggregate accumulator for one downsample interval."""

    __slots__ = ("start", "mn", "mx", "total", "last", "count")

    def __init__(self, start: float, value: float):
        self.start = start
        self.mn = self.mx = self.total = self.last = value
        self.count = 1

    def add(self, value: float) -> None:
        self.mn = min(self.mn, value)
        self.mx = max(self.mx, value)
        self.total += value
        self.last = value
        self.count += 1

    def row(self) -> tuple:
        return (self.start, self.mn, self.mx, self.total / self.count,
                self.last, float(self.count))


class _SeriesTier:
    """One (target, series, tier) directory: the open chunk's append
    state plus seal bookkeeping. Re-attach recovers the open row count
    and the last persisted timestamp by scanning open.jsonl once."""

    def __init__(self, dir_path: str):
        self.dir = dir_path
        self.open_path = os.path.join(dir_path, "open.jsonl")
        self.fh = None
        self.open_rows = 0
        self.open_start: float | None = None
        self.last_ts: float | None = None
        os.makedirs(dir_path, exist_ok=True)
        for row in _read_jsonl(self.open_path):
            self.open_rows += 1
            if self.open_start is None:
                self.open_start = row[0]
            self.last_ts = row[0]

    def append(self, row: tuple) -> None:
        if self.fh is None:
            self.fh = open(self.open_path, "a")
        self.fh.write(json.dumps(list(row)) + "\n")
        self.fh.flush()
        if self.open_start is None:
            self.open_start = row[0]
        self.last_ts = row[0]
        self.open_rows += 1

    def seal(self, series: str, tier: str) -> str | None:
        rows = _read_jsonl(self.open_path)
        if self.fh is not None:
            self.fh.close()
            self.fh = None
        if not rows:
            return None
        path = os.path.join(self.dir, _chunk_name(rows[0][0]))
        write_chunk(path, series, tier, rows)
        os.remove(self.open_path)
        self.open_rows = 0
        self.open_start = None
        get_registry().counter(
            "tsdb_chunks_sealed_total",
            help="tsdb open chunks sealed into immutable CRC'd "
                 "files").inc()
        return path

    def close(self) -> None:
        if self.fh is not None:
            self.fh.close()
            self.fh = None


def _read_jsonl(path: str) -> list[tuple]:
    rows: list[tuple] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # torn tail of a killed writer: routine
                if isinstance(row, list) and row and all(
                        isinstance(v, (int, float)) for v in row):
                    rows.append(tuple(float(v) for v in row))
    except OSError:
        pass
    return rows


class TimeSeriesStore:
    """The embedded store. One instance per collector (or per reading
    tool); every method is thread-safe — the collector scrapes targets
    on parallel threads and a console query may run concurrently."""

    def __init__(self, root: str, *, chunk_samples: int = 360,
                 chunk_span_s: float = 900.0,
                 tiers: tuple = (10.0, 60.0),
                 disk_budget_bytes: int = 64 << 20):
        self.root = root
        self.chunk_samples = max(2, int(chunk_samples))
        self.chunk_span_s = float(chunk_span_s)
        self.tier_widths = tuple(sorted(float(w) for w in tiers))
        self.disk_budget_bytes = int(disk_budget_bytes)
        self._lock = threading.RLock()
        self._states: dict[tuple[str, str, str], _SeriesTier] = {}
        self._buckets: dict[tuple[str, str, float], _Bucket] = {}
        self._pins: dict[str, int] = {}
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------- layout
    def _tier_dir(self, target: str, series: str, tier: str) -> str:
        return os.path.join(self.root, _safe(target), _safe(series), tier)

    def _state(self, target: str, series: str, tier: str) -> _SeriesTier:
        key = (_safe(target), _safe(series), tier)
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = _SeriesTier(
                self._tier_dir(target, series, tier))
        return st

    def targets(self) -> list[str]:
        try:
            return sorted(d for d in os.listdir(self.root)
                          if os.path.isdir(os.path.join(self.root, d)))
        except OSError:
            return []

    def series(self, target: str) -> list[str]:
        tdir = os.path.join(self.root, _safe(target))
        try:
            return sorted(d for d in os.listdir(tdir)
                          if os.path.isdir(os.path.join(tdir, d)))
        except OSError:
            return []

    # ------------------------------------------------------------- writes
    def append(self, target: str, series: str, ts: float,
               value: float) -> None:
        """One raw sample. Updates the online downsample buckets and
        seals/GCs when the open chunk fills — all under one lock, all
        bounded work."""
        ts, value = float(ts), float(value)
        with self._lock:
            st = self._state(target, series, RAW)
            st.append((ts, value))
            for width in self.tier_widths:
                self._downsample(target, series, width, ts, value)
            if (st.open_rows >= self.chunk_samples
                    or (st.open_start is not None
                        and ts - st.open_start >= self.chunk_span_s)):
                st.seal(_safe(series), RAW)
                self.gc()

    def _downsample(self, target: str, series: str, width: float,
                    ts: float, value: float) -> None:
        key = (_safe(target), _safe(series), width)
        start = (ts // width) * width
        b = self._buckets.get(key)
        if b is not None and start > b.start:
            # bucket complete: one aggregate row into the tier's chunk
            tier = _tier_name(width)
            st = self._state(target, series, tier)
            if st.last_ts is None or b.start > st.last_ts:
                # (re-attach guard: a bucket already emitted by the
                # previous process must not appear twice)
                st.append(b.row())
                if st.open_rows >= self.chunk_samples:
                    st.seal(_safe(series), tier)
                    self.gc()
            self._buckets[key] = _Bucket(start, value)
        elif b is None or start < b.start:
            self._buckets[key] = _Bucket(start, value)
        else:
            b.add(value)

    def flush(self) -> None:
        """Seal every open chunk (shutdown / test hook)."""
        with self._lock:
            for (tgt, ser, tier), st in list(self._states.items()):
                if st.open_rows:
                    st.seal(ser, tier)
            self.gc()

    def close(self) -> None:
        with self._lock:
            for st in self._states.values():
                st.close()

    # ------------------------------------------------------------- queries
    def _chunks(self, target: str, series: str, tier: str) -> list[str]:
        d = self._tier_dir(target, series, tier)
        try:
            return sorted(
                os.path.join(d, f) for f in os.listdir(d)
                if f.startswith("chunk-") and f.endswith(".tsc"))
        except OSError:
            return []

    def query_iter(self, target: str, series: str, start: float,
                   end: float, *, tier: str = RAW):
        """Lazy row iterator over [start, end]: sealed chunks (each
        PINNED against GC while it is being read) then the open chunk.
        Rows are ``(ts, value)`` for raw, ``(bucket_ts, min, max, mean,
        last, count)`` for aggregate tiers."""
        for path in self._chunks(target, series, tier):
            with self._lock:
                self._pins[path] = self._pins.get(path, 0) + 1
            try:
                got = read_chunk(path)
                if got is None:
                    continue
                header, rows = got
                if header["end"] < start or header["start"] > end:
                    continue
                for row in rows:
                    if start <= row[0] <= end:
                        yield row
            finally:
                with self._lock:
                    n = self._pins.get(path, 1) - 1
                    if n <= 0:
                        self._pins.pop(path, None)
                    else:
                        self._pins[path] = n
        with self._lock:
            st = self._states.get((_safe(target), _safe(series), tier))
        open_path = (st.open_path if st is not None else os.path.join(
            self._tier_dir(target, series, tier), "open.jsonl"))
        for row in _read_jsonl(open_path):
            if start <= row[0] <= end:
                yield row

    def query(self, target: str, series: str, start: float, end: float,
              *, step: float = 0.0, agg: str = "mean",
              tier: str | None = None) -> list[tuple[float, float]]:
        """Range query: ``[(ts, value), ...]`` sorted by time.

        ``step=0`` returns point samples (raw values, or the ``agg``
        field of aggregate-tier rows). ``step>0`` buckets the range
        into ``[start + k*step)`` windows and reduces each with
        ``agg`` ∈ {min, max, mean, last, count, sum}. ``tier=None``
        picks the coarsest downsample tier that still resolves
        ``step`` (≥ 2 source buckets per output bucket), falling back
        toward raw when a tier holds no data for the range."""
        if agg not in AGGS:
            raise ValueError(f"agg {agg!r} not in {AGGS}")
        tiers_to_try = ([tier] if tier is not None
                        else self._auto_tiers(step))
        rows: list[tuple] = []
        used = RAW
        for t in tiers_to_try:
            rows = sorted(self.query_iter(target, series, start, end,
                                          tier=t), key=lambda r: r[0])
            if rows:
                used = t
                break
        if not rows:
            return []
        if step <= 0.0:
            return [(r[0], _row_value(r, agg, used)) for r in rows]
        out: list[tuple[float, float]] = []
        acc: dict[float, _Agg] = {}
        for r in rows:
            b = start + ((r[0] - start) // step) * step
            a = acc.get(b)
            if a is None:
                a = acc[b] = _Agg()
            a.add(r, used)
        for b in sorted(acc):
            out.append((b, acc[b].value(agg)))
        return out

    def _auto_tiers(self, step: float) -> list[str]:
        picks = [RAW]
        for width in self.tier_widths:
            if step > 0 and width * 2 <= step:
                picks.insert(0, _tier_name(width))
        return picks

    def latest(self, target: str, series: str) -> tuple | None:
        """Newest raw sample on disk (console sparkline anchor)."""
        rows = _read_jsonl(os.path.join(
            self._tier_dir(target, series, RAW), "open.jsonl"))
        if rows:
            return rows[-1]
        chunks = self._chunks(target, series, RAW)
        for path in reversed(chunks):
            got = read_chunk(path)
            if got is not None and got[1]:
                return got[1][-1]
        return None

    # ----------------------------------------------------------- retention
    def gc(self) -> int:
        """Evict oldest sealed chunks until the store fits its disk
        budget. Never the newest sealed chunk of a (target, series,
        tier) — a restarting reader must always find SOME history —
        and never a chunk a live ``query_iter`` holds pinned. Returns
        the number of chunks evicted."""
        with self._lock:
            entries = []  # (start_key, path, size, is_newest)
            total = 0
            for tgt in self.targets():
                for ser in self.series(tgt):
                    base = os.path.join(self.root, tgt, ser)
                    try:
                        tiers = os.listdir(base)
                    except OSError:
                        continue
                    for tier in tiers:
                        chunks = self._chunks(tgt, ser, tier)
                        for i, path in enumerate(chunks):
                            try:
                                size = os.path.getsize(path)
                            except OSError:
                                continue
                            total += size
                            entries.append(
                                (os.path.basename(path), path, size,
                                 i == len(chunks) - 1))
            evicted = 0
            if total > self.disk_budget_bytes:
                for _key, path, size, newest in sorted(entries):
                    if total <= self.disk_budget_bytes:
                        break
                    if newest or self._pins.get(path):
                        continue
                    try:
                        os.remove(path)
                    except OSError:
                        continue
                    total -= size
                    evicted += 1
            if evicted:
                get_registry().counter(
                    "tsdb_gc_evicted_total",
                    help="sealed tsdb chunks evicted by the disk-budget "
                         "retention GC").inc(evicted)
            get_registry().gauge(
                "tsdb_disk_bytes",
                help="bytes of sealed tsdb chunks on disk").set(total)
            return evicted


class _Agg:
    """Reducer that merges raw samples or aggregate-tier rows into one
    output bucket, keeping the math consistent either way: a mean of a
    downsampled range is the sample-count-weighted mean, identical to
    the mean over the raw samples it summarizes."""

    __slots__ = ("mn", "mx", "total", "count", "last")

    def __init__(self):
        self.mn = self.mx = self.last = None
        self.total = 0.0
        self.count = 0.0

    def add(self, row: tuple, tier: str) -> None:
        if tier == RAW:
            mn = mx = last = row[1]
            total, count = row[1], 1.0
        else:
            _ts, mn, mx, mean, last, count = row[:6]
            total = mean * count
        self.mn = mn if self.mn is None else min(self.mn, mn)
        self.mx = mx if self.mx is None else max(self.mx, mx)
        self.total += total
        self.count += count
        self.last = last

    def value(self, agg: str) -> float:
        if agg == "min":
            return self.mn
        if agg == "max":
            return self.mx
        if agg == "last":
            return self.last
        if agg == "count":
            return self.count
        if agg == "sum":
            return self.total
        return self.total / self.count if self.count else 0.0


def _row_value(row: tuple, agg: str, tier: str) -> float:
    if tier == RAW:
        return row[1]
    _ts, mn, mx, mean, last, count = row[:6]
    return {"min": mn, "max": mx, "mean": mean, "last": last,
            "count": count, "sum": mean * count}[agg]
