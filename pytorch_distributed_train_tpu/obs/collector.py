"""Fleet collector: store-discovered scraping + rolling fleet state.

Five per-host observability planes exist (metrics, events, profiler,
perf attribution, request tracing) but each is a file or a port on ONE
host, read after the fact. This module is the fleet-level half: one
collector process (usually ``tools/fleet_console.py``) that

- **discovers** every scrape target through the elastic launcher store
  (``elastic.discover_obs_endpoints``): serving replicas and trainer
  metrics sidecars self-register ``{role, addr, host, gen}`` records
  (``elastic.publish_obs_endpoint``), so a fleet of N hosts needs zero
  static scrape config — the same registry-as-hint stance as the
  serving-replica registry (dead records are fine; staleness here, not
  the registry, decides who is alive);
- **scrapes** ``/metrics`` (Prometheus text v0.0.4, parsed back into
  typed families) and ``/healthz`` (the serving reliability snapshot)
  on a cadence, tracking staleness on the COLLECTOR's monotonic clock
  — receiver-side like ``sentinel/liveness.py``, immune to target
  clock skew, and with the same blame discipline: a target that has
  NEVER been scraped successfully is "never" (still compiling, still
  binding), categorically distinct from one that answered and then
  went silent ("stale" — the alertable condition);
- keeps **bounded rolling state** per host: step + steps/s (derived
  from step deltas on the collector clock), loss, MFU, goodput,
  step-time p50, straggler ratio, input-stage split, shed rate, a
  windowed TTFT p95 estimated from ``serve_ttft_seconds`` bucket
  deltas (responds immediately in BOTH directions, unlike the
  replica's rolling-window p95), serving SLO/admission snapshots,
  checkpoint tier hits, host/device memory headroom, and restart
  generations seen.

The alert engine (obs/alerts.py) evaluates its rule catalog over this
state; ``tools/fleet_console.py`` renders it. No jax anywhere near
this module (obs/ package contract) — it runs on a login host.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request
from collections import deque

from pytorch_distributed_train_tpu.obs.registry import get_registry

# ----------------------------------------------------- exposition parser
_SERIES_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)\s*$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def parse_exposition(text: str) -> dict:
    """Prometheus text v0.0.4 → ``{family: {label_items_tuple: value}}``
    — the inverse of ``registry.render``. Histogram series arrive as
    their ``_bucket``/``_sum``/``_count`` families (with ``le`` labels
    intact), which is exactly what the windowed-quantile estimator
    needs. Unparseable lines are skipped: a scrape is a snapshot, not
    a contract."""
    out: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES_LINE.match(line)
        if not m:
            continue
        name, labels_raw, value_raw = m.groups()
        try:
            value = float(value_raw)
        except ValueError:
            continue
        labels: tuple = ()
        if labels_raw:
            labels = tuple(sorted(
                (k, _unescape(v)) for k, v in _LABEL.findall(labels_raw)))
        out.setdefault(name, {})[labels] = value
    return out


def family_value(families: dict, name: str,
                 labels: dict | None = None) -> float | None:
    """One series' value, or None when absent (the collector's reader)."""
    fam = families.get(name)
    if not fam:
        return None
    key = tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))
    return fam.get(key)


def family_by_label(families: dict, name: str, label: str) -> dict:
    """``{label_value: value}`` for a one-label family (e.g. the
    per-stage input split, the per-tier restore counts)."""
    out: dict[str, float] = {}
    for key, v in (families.get(name) or {}).items():
        for k, lv in key:
            if k == label:
                out[lv] = v
    return out


class HistogramWindow:
    """Windowed quantile over a scraped cumulative histogram.

    Each call diffs the new cumulative bucket counts against the last
    scrape's and reports the requested quantile of ONLY the window's
    observations (nearest bucket upper bound — coarse, but monotone and
    instant to recover, which is what an anomaly detector needs; a
    rolling-window p95 would hold a storm's tail for minutes after it
    ended). Returns None when the window saw no new observations."""

    def __init__(self) -> None:
        self._prev: tuple[dict, float] | None = None

    def observe(self, families: dict, name: str,
                q: float = 0.95) -> float | None:
        buckets: dict[float, float] = {}
        for key, v in (families.get(f"{name}_bucket") or {}).items():
            le = dict(key).get("le")
            if le in (None, "+Inf"):
                continue
            try:
                buckets[float(le)] = v
            except ValueError:
                continue
        total = family_value(families, f"{name}_count") or 0.0
        primed = self._prev is not None
        prev_buckets, prev_total = self._prev or ({}, 0.0)
        self._prev = (buckets, total)
        if not primed:
            # first scrape: the "window" would be the target's whole
            # history — not a window at all; prime and wait for deltas
            return None
        delta_n = total - prev_total
        if delta_n <= 0 or not buckets:
            return None
        uppers = sorted(buckets)
        deltas = {ub: buckets[ub] - prev_buckets.get(ub, 0.0)
                  for ub in uppers}
        if any(d < 0 for d in deltas.values()):
            # Counter reset the total-delta guard cannot see: the
            # target restarted and the NEW process out-accumulated the
            # old total between scrapes (delta_n > 0), but individual
            # buckets went backwards — diffing across generations
            # would fabricate a quantile from a mixed window. Re-prime
            # (the new snapshot is already ``_prev``) and report
            # nothing for this window.
            return None
        target = q * delta_n
        for ub in uppers:
            if deltas[ub] >= target:
                return ub
        # the quantile landed in the implicit +Inf bucket: report past
        # the largest finite bound so the detector still sees "huge"
        return 2.0 * uppers[-1]


# ------------------------------------------------------------- fleet state
# series the alert rules read; every one is a bounded (mono_ts, value)
# deque per target
SERIES = ("step", "steps_per_s", "loss", "step_time_ms", "mfu_pct",
          "goodput_pct", "straggler_ratio", "shed_per_s", "ttft_p95_s",
          # model-health plane (obs/model_health.py): training-dynamics
          # + rollout analytics the early-warning rules read
          "grad_norm", "update_ratio", "reward_mean", "kl_behavior")

# raw scraped families additionally persisted through the history
# store (obs/tsdb.py) when one is attached: the cumulative counters /
# histogram totals the SLO-budget math and postmortems want verbatim,
# not just the per-scrape derivations above
PERSIST_FAMILIES = ("serve_shed_total", "serve_ttft_seconds_count",
                    "serve_ttft_seconds_sum")
PERSIST_FAMILY_SUMS = ("serve_requests_total",)


def family_sum(families: dict, name: str) -> float | None:
    """Sum over every label set of one family (e.g. the per-outcome
    serve_requests_total) — None when the family is absent."""
    fam = families.get(name)
    if not fam:
        return None
    return sum(fam.values())


class Target:
    """One scrape target's rolling state. ``state`` is the staleness
    verdict on the collector's clock:

    - ``never`` — no successful scrape yet (not blamable: first
      compile, late bind — the liveness-plane rule);
    - ``ok``    — answered within ``stale_after_s``;
    - ``stale`` — answered at least once, silent past the deadline
      (the alertable "gone" condition).
    """

    def __init__(self, endpoint: dict, window: int = 240, history=None):
        self.role = str(endpoint.get("role", "?"))
        self.host = str(endpoint.get("host", "?"))
        # durable write-through (obs/tsdb.TimeSeriesStore or None);
        # the key is what slo_budget's role scoping parses back
        self.history = history
        self.history_key = f"{self.role}@{self.host}"
        self._wall_now = 0.0
        self.addr = str(endpoint.get("addr", ""))
        self.idx = int(endpoint.get("idx", -1))
        self.gens: set[str] = set()
        self.note_endpoint(endpoint)
        self.window = window
        self.last_ok_mono: float | None = None
        self.last_attempt_mono: float | None = None
        self.last_error: str | None = None
        self.consecutive_errors = 0
        self.families: dict = {}
        self.healthz: dict | None = None
        self.healthz_code: int | None = None
        self.series: dict[str, deque] = {
            s: deque(maxlen=window) for s in SERIES}
        self.last_step_change_mono: float | None = None
        self._prev_step: tuple[float, float] | None = None  # (mono, step)
        self._prev_counters: dict[str, tuple[float, float]] = {}
        self._ttft_hist = HistogramWindow()
        # latest non-series rollups the console renders
        self.memory: dict = {}
        self.input_split: dict = {}
        self.ckpt_tiers: dict = {}

    def note_endpoint(self, endpoint: dict) -> None:
        """A (re-)registration for this (role, host): newest index wins
        the address; every gen ever seen accumulates (restart count)."""
        if int(endpoint.get("idx", -1)) >= self.idx:
            self.idx = int(endpoint.get("idx", -1))
            self.addr = str(endpoint.get("addr", self.addr))
        self.gens.add(str(endpoint.get("gen", "0")))

    @property
    def gen(self) -> str:
        try:
            return str(max(int(g) for g in self.gens))
        except ValueError:
            return max(self.gens) if self.gens else "0"

    @property
    def restarts(self) -> int:
        return max(0, len(self.gens) - 1)

    def state(self, now_mono: float, stale_after_s: float) -> str:
        if self.last_ok_mono is None:
            return "never"
        if now_mono - self.last_ok_mono > stale_after_s:
            return "stale"
        return "ok"

    def age_s(self, now_mono: float) -> float | None:
        if self.last_ok_mono is None:
            return None
        return now_mono - self.last_ok_mono

    def latest(self, series: str) -> float | None:
        dq = self.series.get(series)
        return dq[-1][1] if dq else None

    # ------------------------------------------------------ derivations
    def _push(self, name: str, now: float, value: float | None) -> None:
        if value is None:
            return
        self.series[name].append((now, float(value)))
        if self.history is not None:
            # wall-clock stamp (set once per ingest): history must be
            # joinable across restarts and against the event journal,
            # which the in-memory deques' monotonic stamps are not
            try:
                self.history.append(self.history_key, name,
                                    self._wall_now or time.time(),
                                    float(value))
            except Exception:
                pass  # history is best-effort; scraping never dies of it

    def _rate(self, name: str, now: float,
              value: float | None) -> float | None:
        """Per-second delta of a scraped counter (None until the second
        sample; counter resets — a restarted target — read as None, not
        a negative rate)."""
        if value is None:
            return None
        prev = self._prev_counters.get(name)
        self._prev_counters[name] = (now, value)
        if prev is None or now <= prev[0] or value < prev[1]:
            return None
        return (value - prev[1]) / (now - prev[0])

    def ingest(self, families: dict, healthz: dict | None,
               healthz_code: int | None, now_mono: float) -> None:
        self.families = families
        self.healthz = healthz
        self.healthz_code = healthz_code
        self.last_ok_mono = now_mono
        self.consecutive_errors = 0
        self.last_error = None
        self._wall_now = time.time()
        if self.history is not None:
            for fname in PERSIST_FAMILIES:
                v = family_value(families, fname)
                if v is None:
                    v = family_sum(families, fname)
                if v is not None:
                    try:
                        self.history.append(self.history_key, fname,
                                            self._wall_now, v)
                    except Exception:
                        pass
            for fname in PERSIST_FAMILY_SUMS:
                v = family_sum(families, fname)
                if v is not None:
                    try:
                        self.history.append(self.history_key, fname,
                                            self._wall_now, v)
                    except Exception:
                        pass

        step = family_value(families, "train_step")
        if step is not None:
            if self._prev_step is None or step != self._prev_step[1]:
                self.last_step_change_mono = now_mono
            if self._prev_step is not None and now_mono > self._prev_step[0]:
                if step >= self._prev_step[1]:
                    self._push("steps_per_s", now_mono,
                               (step - self._prev_step[1])
                               / (now_mono - self._prev_step[0]))
            self._prev_step = (now_mono, step)
            self._push("step", now_mono, step)
        self._push("loss", now_mono, family_value(families, "train_loss"))
        self._push("step_time_ms", now_mono,
                   family_value(families, "train_step_time_ms_p50"))
        self._push("mfu_pct", now_mono,
                   family_value(families, "perf_mfu_pct")
                   if family_value(families, "perf_mfu_pct") is not None
                   else family_value(families, "train_mfu_pct"))
        self._push("goodput_pct", now_mono,
                   family_value(families, "train_goodput_pct"))
        p50_max = family_value(families, "train_step_time_p50_max")
        p50_med = family_value(families, "train_step_time_p50_med")
        if p50_max is not None and p50_med:
            self._push("straggler_ratio", now_mono, p50_max / p50_med)
        self._push("shed_per_s", now_mono,
                   self._rate("serve_shed_total", now_mono,
                              family_value(families, "serve_shed_total")))
        self._push("ttft_p95_s", now_mono,
                   self._ttft_hist.observe(families, "serve_ttft_seconds"))
        # model-health series (absent families push nothing — an image
        # run simply has no reward/KL series): the tree-wide grad norm
        # and worst update-to-param ratio from the in-graph pass, the
        # rollout reward level, and the KL-to-behavior drift. History
        # write-through rides _push like every other series.
        self._push("grad_norm", now_mono,
                   family_value(families, "train_grad_norm"))
        self._push("update_ratio", now_mono,
                   family_value(families, "train_update_ratio_max"))
        self._push("reward_mean", now_mono,
                   family_value(families, "rollout_reward_mean"))
        self._push("kl_behavior", now_mono,
                   family_value(families, "train_kl_behavior"))

        self.memory = {
            k: family_value(families, k)
            for k in ("host_rss_bytes", "host_available_bytes",
                      "device_bytes_in_use", "device_bytes_limit")
            if family_value(families, k) is not None}
        self.input_split = family_by_label(
            families, "input_stage_seconds_total", "stage")
        self.ckpt_tiers = family_by_label(
            families, "ckpt_restore_tier_total", "tier")

    def device_mem_frac(self) -> float | None:
        used = self.memory.get("device_bytes_in_use")
        limit = self.memory.get("device_bytes_limit")
        if used is None or not limit:
            return None
        return used / limit

    def slo(self) -> dict:
        """The serving reliability snapshot out of /healthz, {} for
        trainer targets / pre-plane replicas."""
        if not isinstance(self.healthz, dict):
            return {}
        rel = self.healthz.get("reliability")
        return rel if isinstance(rel, dict) else {}


def _default_fetch(url: str, timeout_s: float) -> tuple[int, bytes]:
    """(status, body); HTTP error statuses still return their body —
    a 503 /healthz carries the draining/error JSON we want."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class FleetCollector:
    """Scrapes every discovered target on a cadence into rolling state.

    ``store_factory`` returns a launcher-store client per call (the
    liveness-plane convention; default ``elastic.worker_store`` — None
    outside a tpurun job). ``endpoints`` seeds static targets for
    store-less runs. ``fetch`` is injectable for tests.
    """

    def __init__(self, *, store_factory=None, endpoints=(),
                 poll_s: float = 2.0, stale_after_s: float = 10.0,
                 window: int = 240, timeout_s: float = 2.0, fetch=None,
                 history=None):
        from pytorch_distributed_train_tpu.elastic import worker_store

        self.poll_s = max(0.05, poll_s)
        self.stale_after_s = stale_after_s
        self.window = window
        self.timeout_s = timeout_s
        # optional durable history (obs/tsdb.TimeSeriesStore): every
        # series sample + selected raw counters write THROUGH it; a
        # fresh collector pointed at the same root re-attaches to the
        # on-disk trajectories (no amnesia gap across restarts)
        self.history = history
        self._factory = store_factory if store_factory is not None \
            else worker_store
        self._fetch = fetch or _default_fetch
        self._lock = threading.Lock()
        self._targets: dict[tuple[str, str], Target] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # persistent resilient store handle for discovery (store_plane):
        # built on first use, kept across passes (it reconnects
        # internally); None until then, or forever when no store is
        # configured — a store-LESS console must not feed the health
        # machine phantom failures
        self._store = None
        self._store_absent = False
        for i, ep in enumerate(endpoints):
            ep = dict(ep)
            ep.setdefault("idx", i)
            self._note_endpoint(ep)

    # ------------------------------------------------------------ targets
    def _note_endpoint(self, ep: dict) -> None:
        key = (str(ep.get("role", "?")), str(ep.get("host", "?")))
        with self._lock:
            t = self._targets.get(key)
            if t is None:
                self._targets[key] = Target(ep, window=self.window,
                                            history=self.history)
            else:
                t.note_endpoint(ep)

    def _store_handle(self):
        """Lazily build the persistent ResilientStore used for
        discovery. Returns None when no store is configured (factory
        yields None) — that's a static-endpoints console, and it must
        never feed the store-health machine phantom failures. A factory
        that RAISES means a store exists but is unreachable: build the
        wrapper anyway so the outage is seen, retried and scored."""
        if self._store is not None or self._store_absent:
            return self._store
        try:
            probe = self._factory()
        except Exception:
            probe = False  # configured-but-down: still wrap
        if probe is None:
            self._store_absent = True
            return None
        if probe is not False:
            try:
                probe.close()
            except Exception:
                pass
        from pytorch_distributed_train_tpu import store_plane

        self._store = store_plane.ResilientStore(
            self._factory, op_timeout_s=self.timeout_s,
            name="fleet-collector")
        return self._store

    def discover(self) -> int:
        """Merge the store's endpoint registry into the target set;
        returns the number of known targets. Store unreachable = keep
        what we have (the fleet does not vanish with a store hiccup):
        the ResilientStore's last-known-good cache keeps serving the
        previous registry through an outage, and with no cache yet the
        OSError is swallowed and the static target set stands."""
        rs = self._store_handle()
        if rs is not None:
            try:
                for ep in rs.discover_obs_endpoints():
                    self._note_endpoint(ep)
            except Exception:
                pass
        with self._lock:
            return len(self._targets)

    def store_health(self) -> dict:
        """Snapshot of the launcher-store health machine (store_plane)
        for the console/alert engine: state, op p95, LKG cache ages.
        Meaningful only once some consumer has run store ops (ops_total
        > 0); store-less deployments read an inert all-zero 'ok'."""
        from pytorch_distributed_train_tpu import store_plane

        return store_plane.health_snapshot()

    @property
    def targets(self) -> list[Target]:
        with self._lock:
            return list(self._targets.values())

    # ------------------------------------------------------------- scrape
    def _scrape_one(self, t: Target, now_mono: float) -> None:
        # Claim under the collector lock: the bare check-then-set raced
        # two concurrent poll() callers (the collector thread + a
        # console tick) into DOUBLE-scraping the same target — exactly
        # the in-flight pile-up the flag exists to prevent (concurrency
        # plane true positive, collector scrape-thread state).
        with self._lock:
            if getattr(t, "_inflight", False):
                return  # a previous (hung) scrape of this target runs
            t._inflight = True
        try:
            self._scrape_locked(t, now_mono)
        finally:
            t._inflight = False

    def _scrape_locked(self, t: Target, now_mono: float) -> None:
        try:
            code, body = self._fetch(f"http://{t.addr}/metrics",
                                     self.timeout_s)
            if code != 200:
                raise OSError(f"/metrics HTTP {code}")
            families = parse_exposition(body.decode("utf-8", "replace"))
            hz_code, hz = None, None
            try:
                hz_code, hz_body = self._fetch(f"http://{t.addr}/healthz",
                                               self.timeout_s)
                hz = json.loads(hz_body.decode("utf-8", "replace"))
            except Exception:
                pass  # metrics answered: the target is alive
            t.ingest(families, hz, hz_code, now_mono)
        except Exception as e:
            t.last_error = f"{type(e).__name__}: {e}"
            t.consecutive_errors += 1
            get_registry().counter(
                "fleet_scrape_errors_total",
                help="failed fleet scrape attempts").inc()
        finally:
            t.last_attempt_mono = now_mono

    def poll(self) -> None:
        """One discovery + scrape pass over every target (the console's
        tick; ``start()`` runs this on the cadence). Targets scrape in
        PARALLEL: one slow or wedged host must not stall every other
        host's staleness clock behind its timeout — that would turn one
        sick target into a fleet-wide false-stale storm. A scrape still
        in flight when the next pass starts is skipped, not doubled."""
        self.discover()
        now = time.monotonic()
        threads = [threading.Thread(target=self._scrape_one,
                                    args=(t, now), daemon=True,
                                    name=f"fleet-scrape-{t.host}")
                   for t in self.targets]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 2.0 * self.timeout_s + 1.0
        for th in threads:
            th.join(timeout=max(0.05, deadline - time.monotonic()))
        reg = get_registry()
        counts = {"never": 0, "ok": 0, "stale": 0}
        for t in self.targets:
            counts[t.state(time.monotonic(), self.stale_after_s)] += 1
        for state, n in counts.items():
            reg.gauge("fleet_targets", labels={"state": state},
                      help="fleet scrape targets by staleness state").set(n)

    # ------------------------------------------------------------ threading
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-collector")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll()
            except Exception:
                pass  # the collector outlives any single bad pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._store is not None:
            try:
                self._store.close()
            except Exception:
                pass
            self._store = None

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """The fleet rollup the console renders and --format json
        emits. Pure read; safe from any thread."""
        now = time.monotonic()
        rows = []
        for t in sorted(self.targets, key=lambda t: (t.role, t.host)):
            slo = t.slo()
            ttft = (slo.get("slo") or {}).get("ttft_s") or {}
            age = t.age_s(now)
            rows.append({
                "host": t.host, "role": t.role, "addr": t.addr,
                "gen": t.gen, "restarts": t.restarts,
                "state": t.state(now, self.stale_after_s),
                "age_s": None if age is None else round(age, 2),
                "error": t.last_error,
                "step": t.latest("step"),
                "steps_per_s": t.latest("steps_per_s"),
                "loss": t.latest("loss"),
                "mfu_pct": t.latest("mfu_pct"),
                "goodput_pct": t.latest("goodput_pct"),
                "step_time_ms": t.latest("step_time_ms"),
                "ttft_p95_s": t.latest("ttft_p95_s"),
                "ttft_rolling": ttft,
                "admission": slo.get("admission"),
                "queue_depth": slo.get("queue_depth"),
                "slots": slo.get("slots"),
                "shed_per_s": t.latest("shed_per_s"),
                # model-health panel input: recent in-window trajectory
                # per series (console sparklines need no history store
                # attached); absent series are omitted entirely so an
                # image run renders no empty panel
                "model_health": {
                    name: [v for _ts, v in t.series[name]]
                    for name in ("grad_norm", "update_ratio",
                                 "reward_mean", "kl_behavior")
                    if t.series[name]},
                "memory": dict(t.memory),
                "input_split": dict(t.input_split),
                "ckpt_tiers": dict(t.ckpt_tiers),
            })
        # slowest: the named-host rollups the ISSUE asks the console for
        trainers = [r for r in rows if r["role"] == "trainer"
                    and r["state"] == "ok"
                    and r["steps_per_s"] is not None]
        serving = [r for r in rows if r["role"] == "serving"
                   and r["state"] == "ok"]

        def _ttft_of(r):
            if r["ttft_p95_s"] is not None:
                return r["ttft_p95_s"]
            return (r["ttft_rolling"] or {}).get("p95") or 0.0

        slowest_trainer = (min(trainers, key=lambda r: r["steps_per_s"])
                           ["host"] if trainers else None)
        slow_serv = [r for r in serving if _ttft_of(r) > 0.0]
        slowest_serving = (max(slow_serv, key=_ttft_of)["host"]
                           if slow_serv else None)
        return {"targets": rows,
                "slowest_trainer": slowest_trainer,
                "slowest_serving": slowest_serving}

    def serving_rows(self) -> list[dict]:
        """The serving-replica load rows (the fleet controller's
        reconcile input): ``snapshot()`` filtered to ``role ==
        "serving"``, each row carrying addr / state / queue_depth /
        admission / shed_per_s / TTFT. Pure read; safe from any
        thread."""
        return [r for r in self.snapshot()["targets"]
                if r["role"] == "serving"]
