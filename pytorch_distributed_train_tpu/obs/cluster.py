"""Cross-host straggler aggregation (SURVEY §5.3a; the Megatron-style
flight-recorder rationale applied to HEALTH numbers, not collectives).

On a multi-host pod every process logs its own step-time percentiles,
but rank-0's console only shows rank-0's numbers — a single straggling
host (thermal throttle, sick NIC, noisy neighbor on its VM) is invisible
until the sustained drill's aggregate gate fails. Here, at log cadence,
every host contributes a small vector of health numbers and rank-0 logs
the cluster min / median / max plus WHICH host is the max — stragglers
become a first-class logged metric instead of a post-mortem discovery.

Mechanics: ``multihost_utils.process_allgather`` over a fixed-order
float vector (keys sorted, so all hosts agree on layout — the same
must-agree contract as debug.check_input_sync). The gather is a blocking
collective: it runs on the consumer thread at log cadence only, never on
the step path, and all hosts call it symmetrically (the call site in
trainer._log_train executes on every process; only the logging after it
is rank-0 gated).

Single-host runs skip the collective entirely and return the degenerate
summary (min=med=max=self, max_host=0) so the logged schema is identical
everywhere — dashboards don't fork on topology.
"""

from __future__ import annotations

import numpy as np


def summarize(local: dict[str, float],
              process_index: int | None = None,
              process_count: int | None = None) -> dict[str, float]:
    """Aggregate per-host health numbers across hosts.

    Returns ``{<key>_min, <key>_med, <key>_max, <key>_max_host}`` for
    every key of ``local``. Keys must be present on ALL hosts (fixed
    schema — the caller builds the dict from always-present meters,
    substituting 0.0 where a backend doesn't report, e.g. hbm on CPU).
    """
    import jax

    n = jax.process_count() if process_count is None else process_count
    keys = sorted(local)
    vec = np.asarray([float(local[k]) for k in keys], np.float64)
    if n <= 1:
        rows = vec[None, :]
    else:
        from jax.experimental import multihost_utils

        rows = np.asarray(multihost_utils.process_allgather(vec))
    out: dict[str, float] = {}
    for j, k in enumerate(keys):
        col = rows[:, j]
        out[f"{k}_min"] = float(np.min(col))
        out[f"{k}_med"] = float(np.median(col))
        out[f"{k}_max"] = float(np.max(col))
        out[f"{k}_max_host"] = int(np.argmax(col))
    return out
