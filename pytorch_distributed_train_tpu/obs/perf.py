"""Performance attribution plane: MFU/roofline, stall stages, perf ledger.

The reliability planes (spans, journal, profiler, sentinel) answer
"what broke"; this module answers "where did the step go" — the
diagnostic instrument every ROADMAP item-2 optimisation is measured
with. Three instruments, one module:

1. **MFU / op-class attribution** — analytic model FLOPs
   (utils/flops.py, optionally cross-checked against jax AOT
   ``cost_analysis()`` via ``utils.flops.aot_fwd_flops_per_item``) over
   the chip's bf16 peak, and the achieved step decomposed into op
   classes (matmul / conv / attention / elementwise / collective /
   infeed — ``utils.xplane.classify_op_class``) from a profiler
   capture's top-ops. Exported as ``perf_mfu_pct`` /
   ``perf_opclass_ms{class=}`` registry gauges and one ``perf``
   journal record per capture (``attribute_capture``, called by the
   managed profiler at window close).

2. **Staged input-pipeline attribution** — the single ``input_stall``
   goodput bucket becomes a per-stage breakdown: datasets and loaders
   time their read / decode / augment work through ``stage(name)``
   and the device assembly path times host→device transfer (``h2d``),
   all accumulated in a process-global :class:`InputStageStats`
   mirrored into ``input_stage_seconds_total{stage=}``. The 2541
   img/s-chip vs 340–445 img/s-host wall (BENCH_LKG) is then "decode is
   83% of the stall", not one opaque bucket. Stage clocks are
   ``time.monotonic()`` (the monotonic-clock pass stance: durations
   must not jump with NTP).

3. **Perf ledger** — an append-only JSONL of throughput/MFU/stall
   rows (:class:`PerfLedger`), written by bench.py and trainer
   summaries, back-importable from the BENCH_r*.json history, and
   gated by a median+MAD regression check that reuses
   ``sentinel.numeric.SpikeDetector`` — ``python -m tools.perf_ledger
   --check`` exits nonzero naming the regressed metric. The
   kernel-gap audit (``kernel_gap_report``) ranks op classes by
   roofline gap per preset from the same rows.

No jax at module scope (the obs/ package contract): data workers and
login-host tools import this without touching a device backend.
"""

from __future__ import annotations

import contextlib
import datetime
import hashlib
import json
import os
import re
import sys
import threading
import time

from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry

# Closed stage vocabulary (docs/performance.md): read = storage bytes →
# host RAM (tar seeks, file opens, fancy-index gathers), decode = JPEG →
# pixels, augment = crop/flip/RandAugment/normalize, h2d = host batch →
# device HBM (make_array_from_process_local_data). Closed so dashboards
# can stack them and the ledger's stall split is comparable across runs.
STAGES = ("read", "decode", "augment", "h2d")

# Default ledger filename — repo-root for bench history, run-dir for
# trainer rows (docs/performance.md).
LEDGER_BASENAME = "PERF_LEDGER.jsonl"
ENV_LEDGER = "PDTT_PERF_LEDGER"


class InputStageStats:
    """Cumulative per-stage input-pipeline seconds.

    Same thread model as data/pipeline.py's StallStats: plain float
    adds under the GIL (decode pools and the producer thread write
    concurrently; a torn read costs a scrape one addend, never a
    crash). Every add also feeds ``input_stage_seconds_total{stage=}``
    so the live split is scrapable without the ledger.
    """

    def __init__(self) -> None:
        self.seconds: dict[str, float] = {s: 0.0 for s in STAGES}
        self.calls: dict[str, int] = {s: 0 for s in STAGES}
        self._counters = {
            s: get_registry().counter(
                "input_stage_seconds_total", labels={"stage": s},
                help="cumulative host input-pipeline seconds by stage "
                     "(read/decode/augment/h2d)")
            for s in STAGES
        }

    def add(self, stage_name: str, dt: float) -> None:
        if stage_name not in self.seconds:  # closed vocabulary
            raise KeyError(
                f"unknown input stage {stage_name!r} (stages: {STAGES})")
        self.seconds[stage_name] += dt
        self.calls[stage_name] += 1
        self._counters[stage_name].inc(dt)

    def merge(self, seconds: dict[str, float]) -> None:
        """Fold another process's stage deltas into this one — the
        shared-memory decode workers (data/workers.py) time their
        read/decode/augment stages process-locally and ship the
        per-batch delta with each result; merging here keeps the
        attribution (and the scrape counters) whole-pipeline even when
        the stages run in forked workers. Unknown stage keys are
        rejected the same way add() rejects them."""
        for name, dt in seconds.items():
            if dt > 0.0:
                self.add(name, dt)

    def snapshot(self) -> dict[str, float]:
        return {s: self.seconds[s] for s in STAGES}

    def split(self) -> dict[str, float]:
        """Normalized stage fractions (sum 1.0), or {} when nothing was
        timed — the ledger's ``stall_split`` field. The split answers
        "when the consumer stalls, which stage is it waiting on": the
        stages' cumulative time shares are the blame proxy (the stall
        itself is one queue.get; only the producer side is staged)."""
        return normalize_split(self.seconds)

    def top_stage(self) -> str | None:
        split = self.split()
        if not split:
            return None
        return max(split, key=split.get)

    def reset(self) -> None:
        for s in STAGES:
            self.seconds[s] = 0.0
            self.calls[s] = 0


def normalize_split(seconds: dict[str, float]) -> dict[str, float]:
    """{stage: seconds} → normalized fractions (sum 1.0), zero stages
    dropped; {} when nothing was timed."""
    total = sum(seconds.values())
    if total <= 0.0:
        return {}
    return {s: round(v / total, 4) for s, v in seconds.items() if v > 0.0}


_STATS: InputStageStats | None = None
_STATS_LOCK = threading.Lock()


def get_input_stats() -> InputStageStats:
    global _STATS
    if _STATS is None:
        with _STATS_LOCK:
            if _STATS is None:
                _STATS = InputStageStats()
    return _STATS


@contextlib.contextmanager
def stage(name: str):
    """``with stage("decode"): ...`` — time one pipeline-stage region
    into the process-global stats. Monotonic clock: stage durations are
    deadline-ish arithmetic inputs (stall splits, regression gates) and
    must not jump with the wall clock."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        get_input_stats().add(name, time.monotonic() - t0)


def _reset_for_tests() -> None:
    global _STATS
    with _STATS_LOCK:
        _STATS = None


# ---------------------------------------------------------------------------
# MFU + op-class attribution
# ---------------------------------------------------------------------------


def record_mfu(mfu_pct: float) -> None:
    """Publish the latest achieved MFU %% as the ``perf_mfu_pct`` gauge
    (trainer log cadence, bench one-shots)."""
    get_registry().gauge(
        "perf_mfu_pct",
        help="latest achieved MFU % (analytic model FLOPs over the "
             "chip's bf16 peak)").set(mfu_pct)


def publish_opclass_split(split_ms: dict[str, float]) -> None:
    """Export one capture's op-class milliseconds as
    ``perf_opclass_ms{class=}`` gauges (closed class vocabulary —
    utils.xplane.PERF_OP_CLASSES — so the label set is bounded)."""
    for cls, ms in split_ms.items():
        get_registry().gauge(
            "perf_opclass_ms", labels={"class": cls},
            help="device milliseconds by op class in the last profiler "
                 "capture").set(ms)


def attribute_capture(logdir: str, step: int | None = None,
                      mfu_pct: float | None = None,
                      top: int = 5) -> dict | None:
    """Attribute one profiler capture: newest xplane dump under
    ``logdir`` → op-class split (ms) + top-ops head, exported as
    gauges and journaled as one ``perf`` record. Returns the
    attribution dict, or None when there is nothing to attribute (no
    dump, or the xplane proto is unavailable in this environment) —
    best-effort by contract: attribution must never fail a capture."""
    try:
        from pytorch_distributed_train_tpu.utils import xplane

        files = xplane.find_xplane_files(logdir)
        if not files:
            return None
        xs = xplane.load_xspace(files[0])
        planes = xplane.summarize_xspace(xs)
        if not planes:  # CPU-only trace (tests): take any plane
            planes = xplane.summarize_xspace(xs, device_only=False)
        if not planes:
            return None
        plane = planes[0]
        split_ms = xplane.opclass_split(plane["ops"])
    except Exception:
        return None
    out = {
        "plane": plane["plane"],
        "total_ms": round(plane["total_ms"], 3),
        "opclass_ms": {c: round(ms, 3) for c, ms in split_ms.items()},
        "top_ops": [(n, round(ms, 3)) for n, ms, _ in plane["ops"][:top]],
    }
    if mfu_pct is not None:
        out["mfu_pct"] = mfu_pct
    publish_opclass_split(split_ms)
    events_lib.emit("perf", "attribution", step=step, dir=logdir, **out)
    return out


# ---------------------------------------------------------------------------
# Perf ledger
# ---------------------------------------------------------------------------


def config_digest(obj) -> str:
    """Short stable digest of a config (dict/json string) — the ledger
    key that tells "same config, new code" rows from config changes."""
    if not isinstance(obj, str):
        obj = json.dumps(obj, sort_keys=True, default=repr)
    return hashlib.sha256(obj.encode()).hexdigest()[:12]


def default_ledger_path(repo_root: str | None = None) -> str:
    """PDTT_PERF_LEDGER env override, else <repo_root>/PERF_LEDGER.jsonl
    (repo root = next to bench.py, two levels above this package)."""
    env = os.environ.get(ENV_LEDGER)
    if env:
        return env
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    return os.path.join(repo_root, LEDGER_BASENAME)


# The ledger keys the regression gate watches. Both are
# higher-is-better, so "spike AND below the median" is a regression.
GATED_KEYS = ("value", "mfu_pct")


class PerfLedger:
    """Append-only JSONL of performance rows.

    Row schema (one JSON object per line; absent keys simply not
    measured that round)::

        {ts, metric, value, unit, mfu_pct, goodput_pct, stall_split,
         opclass_ms, top_ops, config_digest, argv, source, platform}

    Append never rewrites history (the whole point is a trajectory the
    regression gate can trust); a read-only checkout degrades to the
    printed record, same stance as bench.py's LKG store.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    # ------------------------------------------------------------- write
    def append(self, metric: str, value: float, *, unit: str = "",
               source: str = "", config=None, **extra) -> dict:
        row = {"ts": time.time(), "metric": str(metric),
               "value": float(value)}
        if unit:
            row["unit"] = unit
        if source:
            row["source"] = source
        if config is not None:
            row["config_digest"] = config_digest(config)
        for k, v in extra.items():
            if v is not None:
                row[k] = v
        row.setdefault("argv", " ".join(sys.argv[1:]))
        line = json.dumps(row, default=repr)
        with self._lock:
            try:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            except OSError as e:
                print(f"[perf-ledger] append failed ({e}); row not "
                      "persisted", flush=True)
                return row
        get_registry().counter(
            "perf_ledger_rows_total",
            help="perf-ledger rows appended by this process").inc()
        return row

    def append_record(self, record: dict, source: str = "") -> dict | None:
        """Append a bench.py-style record (``{metric, value, unit,
        ...}``); rows without a measured metric (tpu_unavailable) are
        skipped."""
        if not record.get("metric") or record.get("value") is None:
            return None
        extra = {k: v for k, v in record.items()
                 if k not in ("metric", "value", "unit")}
        return self.append(record["metric"], record["value"],
                           unit=record.get("unit", ""), source=source,
                           **extra)

    # -------------------------------------------------------------- read
    def load(self) -> list[dict]:
        rows: list[dict] = []
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn tail line of a killed writer
                    if isinstance(row, dict) and row.get("metric"):
                        rows.append(row)
        except OSError:
            return []
        return rows

    # ------------------------------------------------------------- check
    def check(self, *, min_rows: int = 4, sigma: float = 4.0,
              min_rel: float = 0.05, metrics=None) -> list[dict]:
        """Median+MAD regression gate: for every metric with enough
        history, the NEWEST row's gated keys (throughput ``value``,
        ``mfu_pct``) are judged against the prior rows'
        median — a spike below the median is a regression (reusing the
        sentinel's robust detector so the statistics can't drift from
        the loss-spike plane's). Returns one dict per regression;
        journals each as an ``anomaly``/``perf_regression`` event (the
        timeline landmark)."""
        from pytorch_distributed_train_tpu.sentinel.numeric import (
            SpikeDetector,
        )

        # Grouped by (metric, config_digest): a deliberate config change
        # (different batch/shape under the same metric name) starts its
        # own trajectory instead of reading as a regression — the whole
        # reason rows carry the digest. Rows are ordered by their OWN
        # timestamps, not file position: --import back-fills history
        # with original (file-mtime) stamps, and an imported old round
        # must never be judged as "the newest measurement".
        by_group: dict[tuple, list[dict]] = {}
        for row in self.load():
            key = (row["metric"], row.get("config_digest", ""))
            by_group.setdefault(key, []).append(row)
        out: list[dict] = []
        for (metric, _digest), rows in sorted(by_group.items()):
            if metrics and metric not in metrics:
                continue
            rows.sort(key=lambda r: float(r.get("ts", 0.0)))
            for key in GATED_KEYS:
                if not isinstance(rows[-1].get(key), (int, float)):
                    # the newest row didn't measure this key (CPU run
                    # without mfu_pct): don't re-judge an OLDER row's
                    # value as if it were current
                    continue
                series = [float(r[key]) for r in rows
                          if isinstance(r.get(key), (int, float))]
                if len(series) < min_rows + 1:
                    continue
                prior, newest = series[:-1], series[-1]
                det = SpikeDetector(window=max(len(prior), 2),
                                    sigma=sigma,
                                    min_samples=min_rows,
                                    min_rel=min_rel)
                for v in prior:
                    det.add(v)
                med = sorted(prior)[len(prior) // 2]
                if det.is_spike(newest) and newest < med:
                    reg = {"metric": metric, "key": key,
                           "value": newest, "median": round(med, 4),
                           "n_prior": len(prior)}
                    out.append(reg)
                    get_registry().counter(
                        "perf_regressions_total",
                        help="perf-ledger regression-gate failures"
                    ).inc()
                    events_lib.emit("anomaly", "perf_regression", **reg)
        return out

    # ------------------------------------------------------------ import
    def import_bench_history(self, repo_root: str) -> int:
        """Back-import the BENCH_r*.json round records (driver format:
        ``{"parsed": {metric, value, ...}}``) as ledger rows, stamped
        with their source file and the FILE'S mtime as ``ts`` (not
        import time — the regression gate orders rows by ts, and an
        imported old round must sort into its historical place, never
        after live rows as "the newest measurement"); files already
        imported (a row with the same ``source``) are skipped, so the
        import is idempotent."""
        import glob

        rows0 = self.load()
        have = {r.get("source") for r in rows0}
        # LKG dedupe identity, maintained incrementally as rows append
        # (consecutive outage rounds re-snapshot the same table; a
        # re-read per file would be O(files x ledger))
        seen_meas = {(r.get("metric"), r.get("measured"), r.get("value"))
                     for r in rows0}
        n = 0
        for path in sorted(glob.glob(os.path.join(repo_root,
                                                  "BENCH_r*.json"))):
            src = os.path.basename(path)
            if src in have:
                continue
            try:
                with open(path) as f:
                    rec = json.load(f)
                mtime = os.path.getmtime(path)
            except (OSError, ValueError):
                continue
            parsed = rec.get("parsed") if isinstance(rec, dict) else None
            if not isinstance(parsed, dict):
                continue
            if parsed.get("metric"):
                row = self.append_record({**parsed, "ts": mtime},
                                         source=src)
                if row is not None:
                    n += 1
                continue
            # TPU-outage round (tpu_unavailable): nothing was measured,
            # but a stale round may carry the last-known-good rows the
            # driver snapshotted — prior SUCCESSFUL measurements, each
            # with its own 'measured' date. Import those so the gate
            # judges against the full trajectory instead of a history
            # with an outage-shaped hole. Same idempotency stamp (the
            # whole file's source is in `have` after the first import).
            lkg = (parsed.get("last_known_good") or {}).get("rows")
            if not isinstance(lkg, dict):
                continue
            # consecutive outage rounds re-snapshot the SAME LKG table:
            # dedupe by measurement identity (metric, measured date,
            # value) against everything already in the ledger, or each
            # outage file would re-import identical rows and bias the
            # gate's median toward whichever era wedged more often
            for metric, r in sorted(lkg.items()):
                if not isinstance(r, dict) or r.get("value") is None:
                    continue
                ident = (metric, r.get("measured"), float(r["value"]))
                if ident in seen_meas:
                    continue
                seen_meas.add(ident)
                ts = mtime
                measured = r.get("measured")
                if measured:
                    try:
                        ts = datetime.datetime.strptime(
                            str(measured), "%Y-%m-%d").replace(
                            tzinfo=datetime.timezone.utc).timestamp()
                    except ValueError:
                        pass
                extra = {k: v for k, v in r.items()
                         if k not in ("value", "unit", "measured")}
                row = self.append(metric, r["value"],
                                  unit=r.get("unit", ""), source=src,
                                  ts=ts, measured=measured,
                                  stale_source=True, **extra)
                if row is not None:
                    n += 1
        return n


# ---------------------------------------------------------------------------
# Kernel-gap audit
# ---------------------------------------------------------------------------

# Op classes that do model FLOPs on the MXU; everything else in a step
# is overhead against the roofline (its whole share is gap).
COMPUTE_CLASSES = ("matmul", "conv", "attention")

# The ROADMAP item-2 presets the audit ranks by default.
AUDIT_PRESETS = ("resnet50", "bert_base", "vit_b16")


def kernel_gap(mfu_pct: float, opclass_ms: dict[str, float] | None
               ) -> list[tuple[str, float, float]]:
    """Rank op classes by roofline gap for one measured row.

    With ``achieved = mfu/100`` as the fraction of the step that was
    roofline-ideal work, the remaining ``1 - achieved`` is gap, split
    over classes: a non-compute class's entire time share is gap
    (collectives, infeed, elementwise glue do no model FLOPs); a
    compute class's gap is its share minus its proportional slice of
    the ideal time. The ideal allocation is capped at the compute
    classes' measured share — a capture whose op shares disagree with
    the MFU sample (different steps, approximate classification) must
    not produce negative per-class gaps — so gap shares sum to
    ``1 - min(mfu/100, compute_share)`` exactly (``1 - mfu/100`` when
    the capture's compute share covers the MFU, the normal case).

    Returns ``[(class, time_share, gap_share), ...]`` sorted by gap
    (descending); with no op-class data the whole gap is one
    ``unattributed`` row.
    """
    ideal = max(0.0, min(1.0, mfu_pct / 100.0))
    if not opclass_ms or sum(opclass_ms.values()) <= 0.0:
        return [("unattributed", 1.0, round(1.0 - ideal, 4))]
    total = sum(opclass_ms.values())
    shares = {c: ms / total for c, ms in opclass_ms.items() if ms > 0}
    compute_share = sum(shares.get(c, 0.0) for c in COMPUTE_CLASSES)
    ideal_eff = min(ideal, compute_share)
    out = []
    for cls, share in shares.items():
        if cls in COMPUTE_CLASSES and compute_share > 0:
            gap = share - ideal_eff * (share / compute_share)
        else:
            gap = share
        out.append((cls, round(share, 4), round(max(0.0, gap), 4)))
    out.sort(key=lambda t: -t[2])
    return out


# Op-class → the concrete lever in THIS repo that closes it: the audit
# names where the roofline gap lives; the worklist names what to flip.
# Closed over the classifier's vocabulary (utils.xplane) + the audit's
# synthetic 'unattributed' row.
FUSION_SUGGESTIONS = {
    "elementwise": ("fuse block epilogues: model.fused_epilogues "
                    "(bias+GELU / residual+LayerNorm, ops/"
                    "fused_update.py) + train.fused_epilogue "
                    "(one-pass clip+update+gate optimizer epilogue)"),
    "collective": ("overlap grad reductions: train.overlap_collectives "
                   "+ train.grad_bucket_mb (bucketed in-scan pmeans) "
                   "with the latency-hiding scheduler preset"),
    "infeed": ("input pipeline: data.mp_workers / packed_cache_dir / "
               "device_augment (docs/performance.md, input side)"),
    "attention": ("Pallas flash attention: model.attention_impl=pallas "
                  "(ops/flash_attention.py); chunked as the XLA "
                  "fallback"),
    "matmul": ("int8 quantized training (model.quant_training) or "
               "remat_policy=dots to stop recomputing MXU work"),
    "conv": ("space_to_depth stem (model.stem) and NHWC layout audit "
             "(models/resnet.py)"),
    "unattributed": ("no op-class capture for this row — run with "
                     "obs.profile_every_steps so attribute_capture "
                     "can split the gap"),
}


# bench.py's compute-graph arm tokens (_ga4/_overlap/_fusedep — ISSUE
# 14): arm rows own their OWN ledger trajectories and must never be
# cross-judged as the canonical preset's newest audited row.
_ARM_METRIC = re.compile(r"_(ga\d+|overlap|fusedep)_")


def _newest_audited_row(rows: list[dict], preset: str) -> dict | None:
    row = None
    for r in rows:  # newest wins: rows are append-ordered
        metric = str(r.get("metric", ""))
        if metric.startswith(preset) \
                and not _ARM_METRIC.search(metric) \
                and isinstance(r.get("mfu_pct"), (int, float)):
            row = r
    return row


def fusion_worklist(rows: list[dict], presets=AUDIT_PRESETS,
                    top_n: int = 3) -> list[dict]:
    """Turn the kernel-gap ranking into an ACTIONABLE fusion worklist:
    for each preset's newest audited ledger row, the top-N op-class
    gaps with the row's config digest, the capture/source that measured
    it, and the concrete repo lever that closes that class
    (FUSION_SUGGESTIONS). Consumed by ``tools/perf_ledger --audit
    --suggest`` and obs_report's perf section."""
    out: list[dict] = []
    for preset in presets:
        row = _newest_audited_row(rows, preset)
        if row is None:
            continue
        mfu = float(row["mfu_pct"])
        for cls, share, gap in kernel_gap(mfu, row.get("opclass_ms"))[:top_n]:
            if gap <= 0.0:
                continue
            out.append({
                "preset": preset,
                "metric": row.get("metric"),
                "op_class": cls,
                "gap_share": gap,
                "time_share": share,
                "mfu_pct": mfu,
                "config_digest": row.get("config_digest"),
                "source": row.get("source"),
                "measured": row.get("measured") or row.get("ts"),
                "capture": row.get("capture") or row.get("argv"),
                "suggestion": FUSION_SUGGESTIONS.get(
                    cls, "no catalogued lever — profile deeper"),
            })
    out.sort(key=lambda d: -d["gap_share"])
    return out


def fusion_worklist_report(rows: list[dict], presets=AUDIT_PRESETS,
                           top_n: int = 3) -> str:
    """Rendered worklist (one actionable line per gap entry)."""
    items = fusion_worklist(rows, presets=presets, top_n=top_n)
    if not items:
        return ("fusion worklist: no audited ledger rows (need mfu_pct "
                "rows — run bench.py per preset, or --import history)")
    lines = ["fusion worklist (top kernel-gap classes -> repo lever):"]
    for it in items:
        digest = f" cfg={it['config_digest']}" if it["config_digest"] else ""
        cap = f" [{it['capture']}]" if it.get("capture") else ""
        lines.append(
            f"  {it['preset']:<12} {it['op_class']:<12} "
            f"gap {it['gap_share']:>6.1%} (share {it['time_share']:.1%}, "
            f"{it['mfu_pct']:.1f}% MFU{digest}){cap}")
        lines.append(f"    -> {it['suggestion']}")
    return "\n".join(lines)


def kernel_gap_report(rows: list[dict],
                      presets=AUDIT_PRESETS) -> str:
    """The audit: newest ledger row per preset (metric prefix match)
    that carries ``mfu_pct``, ranked through :func:`kernel_gap`.
    Presets with no measured row say so rather than vanish (a silent
    hole reads as 'audited clean')."""
    lines = ["kernel-gap audit (roofline gap by op class; gap shares "
             "sum to 1 - MFU, capped by the capture's compute share):"]
    for preset in presets:
        row = _newest_audited_row(rows, preset)
        if row is None:
            lines.append(f"  {preset}: no ledger row with mfu_pct — run "
                         f"bench.py --model {preset}")
            continue
        mfu = float(row["mfu_pct"])
        lines.append(f"  {preset}: {row['metric']} = {row['value']} "
                     f"{row.get('unit', '')} @ {mfu:.2f}% MFU "
                     f"(gap {100.0 - mfu:.2f}%)")
        lines.append(f"    {'class':<14} {'time share':>10} "
                     f"{'gap share':>10}")
        for cls, share, gap in kernel_gap(mfu, row.get("opclass_ms")):
            lines.append(f"    {cls:<14} {share:>10.1%} {gap:>10.1%}")
    return "\n".join(lines)
