"""Goodput accounting: decompose cumulative wall time into named buckets.

The Goodput-measurement framing (PAPER.md SURVEY §5.3a context; Google's
"Goodput" for ML training): of all wall-clock seconds a job has been
alive, how many went to PRODUCTIVE training steps vs overhead — compile,
input stalls, checkpointing, eval, and unattributed idle. A run that
reports 95% MFU during steps but spends a third of its life recompiling
or blocked on the input pipeline has terrible goodput, and nothing in a
step-time percentile shows it.

Buckets (fixed vocabulary, so dashboards can stack them):

    init        — Trainer construction (mesh, model init, data, restore)
    compile     — first execution of the jitted train step per fit()
                  (jit compile + the first step's run; the standard
                  host-side attribution — XLA doesn't expose the split
                  without a profiler session)
    step        — train_step dispatch + the host sync absorbed by the
                  NEXT dispatch (the steady-state productive bucket)
    input_stall — blocked in the batch iterator's next() (host pipeline
                  behind; same wait StallStats counts, attributed here
                  to wall time)
    ckpt        — maybe_save / final save / wait_until_finished
    eval        — evaluate() passes
    idle        — everything unattributed (logging, BN re-estimation,
                  inter-epoch bookkeeping)

One optional bucket appears only when the tiered checkpoint plane
produces it (so non-tiered runs keep the exact fixed vocabulary):

    ckpt.drain  — save-boundary waits for a still-in-flight background
                  persist (ckpt/ back-pressure; carved out of ckpt via
                  ``reattribute`` so the two are separable on a
                  dashboard: ckpt = unavoidable snapshot cost,
                  ckpt.drain = storage slower than the save cadence)

``idle`` is computed as wall − Σ(known), so the buckets sum to wall time
EXACTLY by construction; the acceptance tolerance (5%) guards against a
tracker bug making idle negative, not float drift.

``goodput_pct = 100 * step / wall`` — the productive-time definition.
``compile`` is deliberately excluded from the numerator: restart-heavy
jobs (elastic preemption) lose goodput to recompiles and that loss is
the thing this metric exists to surface.
"""

from __future__ import annotations

import contextlib
import time

BUCKETS = ("init", "compile", "step", "input_stall", "ckpt", "eval", "idle")

# The serving-side vocabulary (serving_plane/): the continuous batcher's
# wall time decomposes into admission prefills, batched decode quanta,
# injected/detected stalls, and the idle remainder. ``productive`` for a
# serving loop is prefill+decode — time the chip spent on requests.
SERVE_BUCKETS = ("prefill", "decode", "stalled", "idle")


class GoodputTracker:
    def __init__(self, t0: float | None = None,
                 buckets: tuple[str, ...] = BUCKETS,
                 productive: tuple[str, ...] | str = "step"):
        self.t0 = time.perf_counter() if t0 is None else t0
        self.buckets: dict[str, float] = {b: 0.0 for b in buckets
                                          if b != "idle"}
        # which bucket(s) count as productive in goodput_pct: the train
        # vocabulary's "step", the serving vocabulary's prefill+decode
        self._productive = ((productive,) if isinstance(productive, str)
                            else tuple(productive))

    def account(self, bucket: str, seconds: float) -> None:
        if bucket == "idle":
            raise ValueError("idle is derived (wall - sum), never accounted")
        self.buckets[bucket] = self.buckets.get(bucket, 0.0) + max(0.0, seconds)

    def reattribute(self, from_bucket: str, to_bucket: str,
                    seconds: float) -> None:
        """Move ``seconds`` from one bucket to another — for a callee
        that can split a caller's ``measure()`` window more precisely
        than the caller can (the tiered checkpoint manager carves its
        back-pressure drain out of the trainer's ckpt window). Sum over
        buckets is preserved exactly; the donor may dip negative for
        the instants between this call and the enclosing measure()'s
        account (a scrape race, corrected at window close)."""
        if "idle" in (from_bucket, to_bucket):
            raise ValueError("idle is derived (wall - sum), never accounted")
        seconds = max(0.0, seconds)
        self.buckets[from_bucket] = (
            self.buckets.get(from_bucket, 0.0) - seconds)
        self.buckets[to_bucket] = self.buckets.get(to_bucket, 0.0) + seconds

    @contextlib.contextmanager
    def measure(self, bucket: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.account(bucket, time.perf_counter() - t0)

    # ------------------------------------------------------------ report
    def wall_s(self, now: float | None = None) -> float:
        return (time.perf_counter() if now is None else now) - self.t0

    def snapshot(self, now: float | None = None) -> dict:
        """``{goodput_pct, goodput_wall_s, goodput_s_<bucket>...}`` —
        flat float keys so the dict drops straight into MetricLogger.log
        (and from there into JSONL/TB/scrape)."""
        wall = max(self.wall_s(now), 1e-9)
        known = sum(self.buckets.values())
        out = {f"goodput_s_{b}": round(v, 4)
               for b, v in self.buckets.items()}
        out["goodput_s_idle"] = round(max(0.0, wall - known), 4)
        out["goodput_wall_s"] = round(wall, 4)
        out["goodput_pct"] = round(
            100.0 * sum(self.buckets.get(b, 0.0)
                        for b in self._productive) / wall, 2)
        return out
