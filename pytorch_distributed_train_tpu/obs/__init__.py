"""Unified observability layer (SURVEY §5.3a/§5.5; Goodput-style
accounting per PAPER.md C25/C26).

Four host-side parts, all wired through the existing trainer /
checkpoint / data / serving layers:

- ``spans``     — ``span("checkpoint.save")`` context-manager tracing
                  into a ring buffer (dumped by the watchdog on abort),
                  exportable as Chrome ``trace.json`` for side-by-side
                  viewing with xplane device traces.
- ``registry``  — process-wide counters / gauges / histograms with
                  Prometheus text exposition; ``MetricLogger.log`` feeds
                  it so JSONL, TensorBoard and a scrape see the same
                  numbers.
- ``exposition``— the ``/metrics`` scrape surface: a handler snippet for
                  existing HTTP servers (tools/serve_http.py) and a
                  standalone opt-in sidecar (``cfg.obs.metrics_port``).
- ``cluster``   — cross-host min/median/max (+ arg-max host) of per-host
                  health numbers via ``process_allgather`` — stragglers
                  become a first-class logged metric.
- ``goodput``   — wall-time decomposition into named buckets
                  (init/compile/step/input_stall/ckpt/eval/idle) and the
                  productive-time ``goodput_pct``.
- ``events``    — append-only per-host JSONL journal of structured run
                  events (faults, sentinel verdicts, ckpt traffic,
                  restarts, captures); tools/timeline_report.py merges
                  every host's into one cross-host timeline.
- ``profiler``  — managed ``jax.profiler`` plane: bounded N-step capture
                  windows with an artifact ring, opened on cadence, on
                  demand (trigger file / POST /profile / launcher-store
                  coordination) or by anomaly hooks, each auto-summarized
                  via the xplane top-ops report and journaled.
- ``tracing``   — distributed request tracing (docs/observability.md):
                  W3C-``traceparent``-style context propagated router →
                  replica → batcher → decode, spans carrying
                  trace/span/parent ids + (gen, step)/weight-version
                  correlation tags, and a tail-based sampler spilling
                  retained trees to per-host JSONL beside the journal
                  (``tools/timeline_report.py --trace`` merges them).
- ``perf``      — performance attribution plane (docs/performance.md):
                  MFU/roofline + op-class capture attribution, staged
                  input-pipeline stall timers (read/decode/augment/h2d),
                  and the append-only perf ledger with its median+MAD
                  regression gate (tools/perf_ledger.py).
- ``memory``    — host/device memory-headroom gauges (RSS,
                  MemAvailable, device bytes in use/limit), refreshed
                  at log cadence and on every scrape.
- ``collector`` — the fleet half: store-discovered scraping of every
                  host's /metrics + /healthz into bounded rolling
                  fleet state, staleness on the collector's clock.
- ``alerts``    — the CLOSED declarative alert-rule catalog + engine
                  over the collector's state (threshold / absence /
                  rate / anomaly; firing→resolved lifecycle journaled
                  under the ``alert`` category); rendered live by
                  tools/fleet_console.py.

Everything here is plain-Python host code: no jax import at module
scope except in ``cluster`` (which is lazy), so data-loader worker
processes can use spans/metrics without touching the device backend.
"""

from pytorch_distributed_train_tpu.obs.events import emit, get_journal  # noqa: F401
from pytorch_distributed_train_tpu.obs.goodput import GoodputTracker  # noqa: F401
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: F401
from pytorch_distributed_train_tpu.obs.spans import get_recorder, span  # noqa: F401
