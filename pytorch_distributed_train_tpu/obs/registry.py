"""Process-wide metrics registry with Prometheus text exposition.

The pull complement to the push-style MetricLogger: JSONL/TensorBoard
record history for post-hoc analysis, a scrape answers "what is this
process doing RIGHT NOW" without touching the run directory. One
registry per process (``get_registry``); ``MetricLogger.log`` mirrors
every numeric metric into it as a gauge, so the scrape and the JSONL
always agree — no second bookkeeping path to drift.

Instruments (the standard Prometheus trio, stdlib-only):
- ``Counter``   — monotonically increasing float (``_total`` names).
- ``Gauge``     — set-to-current value.
- ``Histogram`` — cumulative buckets + ``_sum``/``_count`` (classic
  Prometheus ``le`` semantics). Default buckets are exponential from
  1 ms to ~2 min — sized for step/span durations in seconds.

Exposition follows the text format v0.0.4 (``# HELP`` / ``# TYPE`` then
one line per labeled series); ``render()`` is what both the serve_http
``/metrics`` route and the trainer sidecar (obs/exposition.py) return.

Thread model: get-or-create goes through one lock; the hot mutators
(inc/set/observe) are plain float ops under the GIL — same stance as
data/pipeline.py's StallStats. A scrape may see a histogram mid-update
(count ahead of sum by one observation); Prometheus scrapes tolerate
that by design.
"""

from __future__ import annotations

import threading
import time

# step/span durations in SECONDS: 1ms .. ~131s, doubling
_DEFAULT_BUCKETS = tuple(0.001 * 2 ** i for i in range(18))

_INVALID = str.maketrans(
    {c: "_" for c in r"""!"#$%&'()*+,-./;<=>?@[\]^`{|}~ """})


def sanitize_name(name: str) -> str:
    """Metric-name charset is [a-zA-Z_:][a-zA-Z0-9_:]*; JSONL keys like
    ``step_time_ms_p50`` pass through, ``grad_norm/encoder`` does not."""
    name = str(name).translate(_INVALID)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _fmt_labels(labels: tuple[tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


class Counter:
    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Gauge:
    def __init__(self) -> None:
        self.value = 0.0
        self.updated_at = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)
        self.updated_at = time.time()


class Histogram:
    def __init__(self, buckets: tuple[float, ...] = _DEFAULT_BUCKETS):
        self.uppers = tuple(sorted(buckets))
        self.counts = [0] * len(self.uppers)  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.sum += v
        self.count += 1
        for i, ub in enumerate(self.uppers):
            if v <= ub:
                self.counts[i] += 1
                break
        # above the last bound: lands only in the implicit +Inf bucket

    def cumulative(self) -> list[tuple[float, int]]:
        out, acc = [], 0
        for ub, c in zip(self.uppers, self.counts):
            acc += c
            out.append((ub, acc))
        return out


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> (type, help, {label_items_tuple: instrument})
        self._families: dict[str, tuple[str, str, dict]] = {}

    # ------------------------------------------------------ get-or-create
    def _get(self, kind: str, name: str, labels: dict | None, help: str,
             factory):
        name = sanitize_name(name)
        key = tuple(sorted((str(k), str(v))
                           for k, v in (labels or {}).items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (kind, help, {})
                self._families[name] = fam
            if fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, "
                    f"requested {kind}")
            inst = fam[2].get(key)
            if inst is None:
                inst = fam[2][key] = factory()
            return inst

    def counter(self, name: str, labels: dict | None = None,
                help: str = "") -> Counter:
        return self._get("counter", name, labels, help, Counter)

    def gauge(self, name: str, labels: dict | None = None,
              help: str = "") -> Gauge:
        return self._get("gauge", name, labels, help, Gauge)

    def histogram(self, name: str, labels: dict | None = None,
                  help: str = "",
                  buckets: tuple[float, ...] | None = None) -> Histogram:
        return self._get("histogram", name, labels, help,
                         lambda: Histogram(buckets or _DEFAULT_BUCKETS))

    # --------------------------------------------------------- bulk feed
    def set_from_mapping(self, metrics: dict, prefix: str = "") -> None:
        """Mirror a MetricLogger record: every numeric value becomes a
        gauge ``<prefix>_<key>`` (non-numerics skipped). Called on every
        ``log``, so the scrape always shows the latest logged window.

        Per-module keys (``grad_norm/<module>`` and the model-health
        families — obs/model_health.py) route through a bounded
        ``module=`` label: ``sanitize_name`` would otherwise fold the
        module path into the family NAME (one unbounded family per
        module, and a different spelling per model), dropping them off
        every fixed-name scrape consumer. ``train_grad_norm{module=
        "encoder"}`` is one family however many blocks the model has."""
        for k, v in metrics.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            key = str(k)
            if "/" in key:
                family, _, module = key.partition("/")
                name = sanitize_name(
                    f"{prefix}_{family}" if prefix else family)
                self.gauge(name, labels={"module": module}).set(v)
                continue
            name = sanitize_name(f"{prefix}_{k}" if prefix else k)
            self.gauge(name).set(v)

    # ------------------------------------------------------------ readers
    def get_value(self, name: str, labels: dict | None = None) -> float | None:
        """Current value of one counter/gauge series, or None when the
        series doesn't exist (tests + tools read back what the fault
        layer counted without parsing the text exposition)."""
        name = sanitize_name(name)
        key = tuple(sorted((str(k), str(v))
                           for k, v in (labels or {}).items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam[0] == "histogram":
                return None
            inst = fam[2].get(key)
            return None if inst is None else float(inst.value)

    def family_total(self, name: str) -> float:
        """Sum over every label set of a counter/gauge family (0.0 when
        absent) — e.g. retries_total across all fault points."""
        name = sanitize_name(name)
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam[0] == "histogram":
                return 0.0
            return float(sum(inst.value for inst in fam[2].values()))

    def counter_values(self) -> dict:
        """{(name, label_items_tuple): value} for every counter series —
        the snapshot half of cross-process counter shipping (the
        shared-memory decode workers diff two of these per batch and
        send the delta home; data/workers.py)."""
        out = {}
        with self._lock:
            for name, (kind, _help, series) in self._families.items():
                if kind != "counter":
                    continue
                for key, inst in series.items():
                    out[(name, key)] = float(inst.value)
        return out

    def merge_counter_deltas(self, deltas: dict) -> None:
        """Apply {(name, label_items_tuple): delta} increments — the
        receive half of cross-process counter shipping. Families are
        get-or-created (helpless when new here; the owning module's
        registration sets help on first local use)."""
        for (name, key), dv in deltas.items():
            if dv <= 0:
                continue
            self.counter(name, labels=dict(key)).inc(dv)

    # ---------------------------------------------------------- renderer
    def render(self) -> str:
        """Prometheus text format v0.0.4."""
        lines: list[str] = []
        with self._lock:
            fams = {n: (k, h, dict(series))
                    for n, (k, h, series) in sorted(self._families.items())}
        for name, (kind, help, series) in fams.items():
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")
            for key, inst in series.items():
                if kind == "histogram":
                    for ub, acc in inst.cumulative():
                        le = 'le="%s"' % _fmt_value(ub)
                        lines.append(
                            f"{name}_bucket{_fmt_labels(key, le)} {acc}")
                    inf_le = 'le="+Inf"'
                    lines.append(
                        f"{name}_bucket{_fmt_labels(key, inf_le)}"
                        f" {inst.count}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(key)}"
                        f" {_fmt_value(inst.sum)}")
                    lines.append(f"{name}_count{_fmt_labels(key)}"
                                 f" {inst.count}")
                else:
                    lines.append(f"{name}{_fmt_labels(key)}"
                                 f" {_fmt_value(inst.value)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every family — tests only (the process registry is
        otherwise append-only for scrape stability)."""
        with self._lock:
            self._families.clear()


_GLOBAL: MetricsRegistry | None = None
_GLOBAL_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = MetricsRegistry()
    return _GLOBAL
