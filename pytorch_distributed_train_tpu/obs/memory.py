"""Host + device memory telemetry gauges — the OOM-headroom inputs.

Every plane so far measures *time*; nothing scrapable measures *space*,
and memory exhaustion is the classic silent killer on both sides of the
fleet: a host whose page cache is gone decodes at disk speed long
before the OOM killer fires, and a device a few hundred MB from its
HBM limit fails on the next sharding change. These four gauges are the
first alert-rule inputs (obs/alerts.py ``host_oom_risk`` /
``device_oom_risk``):

- ``host_rss_bytes``       — this process's resident set (VmRSS).
- ``host_available_bytes`` — MemAvailable of the whole host: what the
  kernel estimates can still be allocated without swapping, the number
  the OOM killer effectively budgets against.
- ``device_bytes_in_use``  — accelerator memory in use on local device
  0 (jax ``memory_stats``; best-effort per backend).
- ``device_bytes_limit``   — that device's allocatable limit.

Sampling is best-effort and cheap (two /proc reads); it runs at the
trainer's log cadence and at every ``/metrics`` scrape
(obs/exposition.py ``render_metrics``), so serving replicas get the
gauges without touching their request path. Device stats are only read
when jax is ALREADY imported in this process — the scrape surface must
never pay (or trigger) a backend init, and processes that never touch
a device (the elastic agent, the fleet console) simply don't report
the device pair. No jax at module scope (the obs/ package contract).
"""

from __future__ import annotations

import sys

from pytorch_distributed_train_tpu.obs.registry import get_registry


def host_memory_bytes() -> dict:
    """{"rss": ..., "available": ...} from /proc, missing keys where the
    platform doesn't provide the file (macOS, exotic containers)."""
    out: dict[str, int] = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss"] = int(line.split()[1]) * 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    out["available"] = int(line.split()[1]) * 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    return out


def device_memory_bytes() -> dict:
    """{"in_use": ..., "limit": ...} of local device 0, or {} when jax
    is not already loaded or the backend reports no memory stats (CPU).
    Reading this NEVER imports jax — see module doc."""
    if "jax" not in sys.modules:
        return {}
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return {}
    out: dict[str, int] = {}
    if "bytes_in_use" in stats:
        out["in_use"] = int(stats["bytes_in_use"])
    # backends disagree on the limit key; take the first one present
    for key in ("bytes_limit", "bytes_reservable_limit",
                "pool_bytes"):
        if stats.get(key):
            out["limit"] = int(stats[key])
            break
    return out


def sample_memory_gauges() -> dict:
    """Refresh the four gauges in the process registry; returns the
    sampled values (callers that also want them in a log record)."""
    reg = get_registry()
    host = host_memory_bytes()
    dev = device_memory_bytes()
    sampled: dict[str, int] = {}
    if "rss" in host:
        sampled["host_rss_bytes"] = host["rss"]
        reg.gauge("host_rss_bytes",
                  help="resident set size of this process").set(host["rss"])
    if "available" in host:
        sampled["host_available_bytes"] = host["available"]
        reg.gauge("host_available_bytes",
                  help="kernel MemAvailable estimate for the whole host "
                       "(the OOM-headroom input)").set(host["available"])
    if "in_use" in dev:
        sampled["device_bytes_in_use"] = dev["in_use"]
        reg.gauge("device_bytes_in_use",
                  help="accelerator memory in use on local device 0 "
                       "(best-effort per backend)").set(dev["in_use"])
    if "limit" in dev:
        sampled["device_bytes_limit"] = dev["limit"]
        reg.gauge("device_bytes_limit",
                  help="allocatable accelerator memory limit on local "
                       "device 0").set(dev["limit"])
    return sampled
