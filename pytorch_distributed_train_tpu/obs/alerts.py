"""Declarative fleet alert rules: a CLOSED catalog + the engine.

The fault-points/event-categories pattern applied to alerting: every
rule the fleet plane can fire is declared HERE, in ``RULES``, with its
kind and inputs — and the catalog is cross-checked against the table
in docs/observability.md by the ``alert-catalog`` pass of
``python -m tools.analyze`` (both directions). An alert nobody can
look up is noise; an alert that exists only in a dashboard config is
a silent gap.

Rule kinds over the collector's rolling state (obs/collector.py):

- ``threshold`` — latest value crosses a fixed bound (OOM headroom).
- ``absence``   — something expected stopped happening: a target that
  answered and then went silent (``fleet_stale``; never-scraped
  targets are categorically exempt, the liveness-plane blame rule),
  or a trainer that scrapes fine but whose step counter stopped
  (``trainer_step_stalled``).
- ``rate``      — too many discrete events per window: restart churn
  counted from endpoint-registry generations.
- ``anomaly``   — ``sentinel/numeric.SpikeDetector`` (median + MAD,
  healthy-only window) pointed at a scraped series: step-time, TTFT
  p95, goodput, shed rate, straggler ratio, loss. ``direction``
  filters which side fires (a goodput SPIKE is good news);
  ``min_abs`` floors the deviation so an all-zero baseline (shed
  rate) doesn't make the first 10^-6 a 6-sigma event.
- ``burn_rate`` — Google-SRE multi-window error-budget burn over the
  durable history store (obs/tsdb.py + obs/slo_budget.py): one fast
  (5m/1h, page) and one slow (30m/6h, warn) rule per declared SLO.
  Fires when BOTH windows of the pair burn over ``factor`` (the short
  window proves it is happening NOW, the long one that it is not a
  blip); resolves when either recovers. Needs an engine with an
  attached ``slo_tracker`` — without one (or without history for the
  window) the rules are silent, not failing.

Every FIRED transition mints an alert id (``rule@host@epoch_ms``)
that threads through the journal records (fired / profile_requested /
resolved) — the handle ``tools/postmortem.py --alert`` reconstructs
an incident from.

Lifecycle per (rule, target): untriggered → FIRING → RESOLVED, each
transition journaled under the closed ``alert`` event category (with
the target's host/gen tags — a timeline_report landmark), counted in
``alerts_fired_total{rule=}``, and mirrored in the
``alerts_firing{rule=}`` gauge (the number of targets currently
firing that rule). Per-rule ``cooldown_s`` bounds re-fire chatter.
Transitions optionally POST to a webhook and/or append to a JSONL
file sink, and a firing anomaly rule may invoke the managed profiler
on the offending target (``profile_on_alert`` → ``POST /profile`` on
its scrape endpoint — the PR-5 route exists on both the trainer
sidecar and serve_http), wall-clock cooldown-limited.

Stdlib + sentinel/numeric only; no jax (runs on a login host).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request

from pytorch_distributed_train_tpu.obs import events as events_lib
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.sentinel.numeric import SpikeDetector


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declared rule. ``series`` names the collector series (or
    derived field) the rule reads; ``roles`` scopes it to trainer /
    serving targets."""

    name: str
    kind: str          # threshold | absence | rate | anomaly | burn_rate
    roles: tuple                   # ("trainer",) / ("serving",) / both
    series: str
    description: str
    # threshold bounds (exactly one set for kind=threshold)
    below: float | None = None
    above: float | None = None
    # anomaly detector knobs (kind=anomaly)
    sigma: float = 6.0
    min_samples: int = 8
    min_rel: float = 0.25
    min_abs: float = 0.0
    window: int = 64
    direction: str = "both"        # above | below | both
    resolve_after: int = 2         # consecutive healthy obs to resolve
    # anomaly series can go QUIET (a sparse series like ttft_p95_s
    # only produces samples while requests complete): with no fresh
    # samples there is no evidence either way, and a firing alert
    # would freeze FIRING forever — wedging every consumer that waits
    # for resolution (the fleet controller's calm gate). After this
    # many seconds without a sample, resolve the alert: "no traffic"
    # is not "regressed" (wedged targets are the stale/absence rules'
    # job). 0 disables (dense series like shed_per_s never go quiet).
    quiet_resolve_s: float = 0.0
    # absence / rate windows (seconds)
    for_s: float = 0.0
    # lifecycle
    cooldown_s: float = 60.0
    profile: bool = False          # may invoke the managed profiler
    # burn-rate knobs (kind=burn_rate; windows override-shrinkable for
    # drills via --rule, like every other field)
    slo: str = ""                  # SLO_CATALOG name the rule burns
    burn_window: str = ""          # "fast" | "slow"
    short_s: float = 0.0
    long_s: float = 0.0
    factor: float = 1.0            # burn-rate threshold for BOTH windows


_BOTH = ("trainer", "serving")


def _burn_rules() -> list[AlertRule]:
    """Two multi-window burn-rate rules per declared SLO — derived
    from the SLO catalog so adding an SLO grows its alerting for free
    (the doc table + slo-catalog pass keep the pair honest)."""
    from pytorch_distributed_train_tpu.obs.slo_budget import (
        BURN_FACTORS,
        BURN_WINDOWS,
        SLO_CATALOG,
    )

    out = []
    for slo in SLO_CATALOG.values():
        for win, (short_s, long_s) in sorted(BURN_WINDOWS.items()):
            out.append(AlertRule(
                name=f"slo_{slo.name}_burn_{win}", kind="burn_rate",
                roles=slo.roles, series=slo.series, slo=slo.name,
                burn_window=win, short_s=short_s, long_s=long_s,
                factor=BURN_FACTORS[win],
                profile=(win == "fast"),
                description=f"{slo.name} error budget burning ≥"
                            f"{BURN_FACTORS[win]}× the SLO rate over "
                            f"both the {int(short_s)}s and "
                            f"{int(long_s)}s windows "
                            f"({'page' if win == 'fast' else 'warn'})"))
    return out

# The CLOSED catalog — docs/observability.md '## Alert catalog' mirrors
# this table; tools/analyze's alert-catalog pass keeps the two in sync.
RULES: dict[str, AlertRule] = {r.name: r for r in (
    AlertRule(
        name="fleet_stale", kind="absence", roles=_BOTH, series="scrape",
        description="a target that answered at least once has not been "
                    "scraped successfully past the staleness deadline "
                    "(never-scraped targets are exempt)"),
    AlertRule(
        name="trainer_step_stalled", kind="absence", roles=("trainer",),
        series="step", for_s=120.0,
        description="scrapes succeed but the step counter has not "
                    "advanced for the window — a wedged loop the host's "
                    "own watchdog may be blind to"),
    AlertRule(
        name="loss_spike", kind="anomaly", roles=("trainer",),
        series="loss", direction="above", min_rel=0.5, profile=True,
        description="train loss deviates above the rolling median+MAD "
                    "window (the sentinel spike detector, fleet-side)"),
    AlertRule(
        name="step_time_regression", kind="anomaly", roles=("trainer",),
        series="step_time_ms", direction="above", profile=True,
        description="step-time p50 regressed vs its healthy window"),
    AlertRule(
        name="ttft_regression", kind="anomaly", roles=("serving",),
        series="ttft_p95_s", direction="above", min_abs=0.02,
        profile=True, quiet_resolve_s=30.0,
        description="windowed TTFT p95 (serve_ttft_seconds bucket "
                    "deltas) spiked vs its healthy window"),
    AlertRule(
        name="goodput_drop", kind="anomaly", roles=("trainer",),
        series="goodput_pct", direction="below", min_abs=5.0,
        description="goodput %% fell hard vs its healthy window"),
    AlertRule(
        name="shed_storm", kind="anomaly", roles=("serving",),
        series="shed_per_s", direction="above", min_abs=1.0,
        description="admission-control shed rate spiked (requests/s "
                    "refused with 429)"),
    AlertRule(
        name="straggler_ratio", kind="anomaly", roles=("trainer",),
        series="straggler_ratio", direction="above", min_abs=0.5,
        description="cluster max/median step-time ratio spiked — one "
                    "host is pulling away from the gang"),
    AlertRule(
        name="grad_norm_spike", kind="anomaly", roles=("trainer",),
        series="grad_norm", direction="above", min_rel=0.5, profile=True,
        description="global gradient norm deviates above its healthy "
                    "median+MAD window — the divergence PRECURSOR the "
                    "model-health plane watches; fires steps before "
                    "loss_spike can"),
    AlertRule(
        name="reward_collapse", kind="anomaly", roles=("trainer",),
        series="reward_mean", direction="below", min_rel=0.5,
        profile=True, quiet_resolve_s=60.0,
        description="rollout reward mean fell hard vs its healthy "
                    "window — the online policy is degrading (or the "
                    "reward fn broke)"),
    AlertRule(
        name="kl_runaway", kind="anomaly", roles=("trainer",),
        series="kl_behavior", direction="above", min_abs=0.05,
        profile=True, quiet_resolve_s=60.0,
        description="sampled-token KL to the behavior policy spiked — "
                    "rollouts no longer resemble the policy being "
                    "trained (swap cadence lagging, or the update "
                    "blew past the clip)"),
    AlertRule(
        name="host_oom_risk", kind="threshold", roles=_BOTH,
        series="host_available_bytes", below=1 << 30,
        description="host MemAvailable under the floor (default 1 GiB) "
                    "— decode slowdown, then the OOM killer"),
    AlertRule(
        name="device_oom_risk", kind="threshold", roles=_BOTH,
        series="device_mem_frac", above=0.92,
        description="device bytes_in_use over 92%% of bytes_limit — "
                    "HBM headroom nearly gone"),
    AlertRule(
        name="restart_churn", kind="rate", roles=_BOTH, series="gens",
        above=3, for_s=600.0,
        description="3+ restart generations registered within the "
                    "window — a crash loop, fleet-visible"),
    AlertRule(
        name="store_degraded", kind="threshold", roles=("store",),
        series="store_health_state", above=0.5,
        description="the launcher-store health machine left ok "
                    "(degraded/down) — control-plane outage, not a "
                    "fleet problem; fleet_stale is suppressed while "
                    "this fires so a store blackout never masquerades "
                    "as dead hosts"),
    *_burn_rules(),
)}


class _StoreTarget:
    """The synthetic target the ``store_degraded`` rule fires against:
    there is exactly one launcher store per fleet, and it is not a
    scrape endpoint — its 'series' is the store_plane health machine
    read through ``collector.store_health()``."""

    host = "launcher"
    role = "store"
    gen = "-"


_STORE_TARGET = _StoreTarget()


class _RuleState:
    """Lifecycle of one (rule, target) pair."""

    def __init__(self, rule: AlertRule):
        self.firing = False
        self.since_mono: float | None = None
        self.last_fire_mono: float | None = None
        self.healthy = 0
        self.value: float | None = None
        self.baseline: float | None = None
        self.detector: SpikeDetector | None = None
        self.last_sample_mono: float | None = None
        self.alert_id: str | None = None  # minted at FIRE, threads
        # through resolve/profile journal records (postmortem handle)
        if rule.kind == "anomaly":
            self.detector = SpikeDetector(
                window=rule.window, sigma=rule.sigma,
                min_samples=rule.min_samples, min_rel=rule.min_rel)


class AlertEngine:
    """Evaluates the rule catalog over a FleetCollector each tick.

    ``sink_path`` appends one JSON record per transition;
    ``webhook_url`` POSTs the same record (both best-effort — alerting
    must never take the console down). ``overrides`` maps
    ``rule.field`` → value (the console's ``--rule`` flag) so a drill
    can tighten ``min_samples``/``cooldown_s`` without code edits.
    """

    def __init__(self, *, rules: dict | None = None,
                 stale_after_s: float | None = None,
                 sink_path: str = "", webhook_url: str = "",
                 profile_on_alert: bool = False,
                 profile_cooldown_s: float = 300.0,
                 profile_capture_s: float = 2.0,
                 overrides: dict | None = None, opener=None,
                 slo_tracker=None):
        base = dict(rules if rules is not None else RULES)
        for spec, value in (overrides or {}).items():
            rule_name, _, field = spec.partition(".")
            if rule_name not in base or not hasattr(base[rule_name],
                                                    field):
                raise KeyError(f"unknown rule override {spec!r}")
            cur = getattr(base[rule_name], field)
            if isinstance(cur, bool):
                value = str(value).lower() in ("1", "true", "yes")
            elif isinstance(cur, int):
                value = int(float(value))
            elif isinstance(cur, float) or cur is None:
                try:
                    value = float(value)
                except (TypeError, ValueError):
                    pass  # a string field (direction) stays a string
            base[rule_name] = dataclasses.replace(
                base[rule_name], **{field: value})
        self.rules = base
        self.stale_after_s = stale_after_s
        self.sink_path = sink_path
        self.webhook_url = webhook_url
        self.profile_on_alert = profile_on_alert
        self.profile_cooldown_s = profile_cooldown_s
        self.profile_capture_s = profile_capture_s
        # obs/slo_budget.SLOBudgetTracker over the history store; the
        # burn_rate rules are inert without one
        self.slo_tracker = slo_tracker
        self._opener = opener or urllib.request.urlopen
        self._states: dict[tuple[str, str, str], _RuleState] = {}
        self._gen_seen: dict[tuple[str, str], dict[str, float]] = {}
        self._store_suppress = False  # set each tick by _eval_store
        self._last_profile_mono: float | None = None
        # action-sink hook (fleet/controller.py): every transition
        # record is pushed to subscribers as it happens, so a
        # controller reacts on the evaluation tick instead of diffing
        # firing() snapshots
        self._subscribers: list = []

    def subscribe(self, fn) -> None:
        """Register ``fn(rec)`` to receive every transition record
        (fired AND resolved, each carrying the incident ``id``).
        Subscriber errors are swallowed — an actuator bug must never
        take alert evaluation down."""
        self._subscribers.append(fn)

    # ------------------------------------------------------------ helpers
    def _state(self, rule: AlertRule, target) -> _RuleState:
        key = (rule.name, target.role, target.host)
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = _RuleState(rule)
        return st

    def firing(self) -> list[dict]:
        """Currently-firing alerts (console's active list), with ages."""
        now = time.monotonic()
        out = []
        for (rule, role, host), st in sorted(self._states.items()):
            if st.firing:
                out.append({
                    "rule": rule, "role": role, "host": host,
                    "for_s": round(now - (st.since_mono or now), 1),
                    "value": st.value, "baseline": st.baseline,
                    "id": st.alert_id})
        return out

    # -------------------------------------------------------- transitions
    def _transition(self, rule: AlertRule, target, st: _RuleState,
                    fire: bool, now_mono: float,
                    value: float | None, baseline: float | None) -> dict:
        st.firing = fire
        st.value = value
        st.baseline = baseline
        rec = {"rule": rule.name, "kind": rule.kind, "host": target.host,
               "role": target.role, "gen": target.gen}
        if value is not None:
            rec["value"] = round(float(value), 6)
        if baseline is not None:
            rec["baseline"] = round(float(baseline), 6)
        if fire:
            st.since_mono = now_mono
            st.last_fire_mono = now_mono
            st.healthy = 0
            # the incident handle: stable across this firing's whole
            # lifecycle, unique enough per journal (same rule+host
            # cannot fire twice in one millisecond)
            st.alert_id = (f"{rule.name}@{target.host}"
                           f"@{int(time.time() * 1000)}")
            rec["event"] = "fired"
            get_registry().counter(
                "alerts_fired_total", labels={"rule": rule.name},
                help="alert-rule firing transitions").inc()
        else:
            rec["event"] = "resolved"
            rec["after_s"] = round(now_mono - (st.since_mono or now_mono), 1)
            st.since_mono = None
        # the incident id rides EVERY transition record — resolve
        # included, so action→resolve chains close without the caller
        # re-deriving rule@host@ms from parts
        if st.alert_id is not None:
            rec["id"] = st.alert_id
        events_lib.emit("alert", rec["event"], rule=rule.name,
                        host=target.host, role=target.role,
                        gen=target.gen,
                        **{k: v for k, v in rec.items()
                           if k in ("value", "baseline", "after_s",
                                    "id")})
        if not fire:
            # the id's lifetime IS the incident's: once the resolve
            # record carried it out, a later unrelated firing must mint
            # a fresh one, never inherit this one
            st.alert_id = None
        self._sink(rec)
        for fn in self._subscribers:
            try:
                fn(rec)
            except Exception:
                pass  # subscriber bugs must not break evaluation
        if fire and rule.profile and self.profile_on_alert:
            self._request_profile(rule, target, now_mono,
                                  rec.get("id"))
        return rec

    def _sink(self, rec: dict) -> None:
        payload = dict(rec, ts=time.time())
        if self.sink_path:
            try:
                with open(self.sink_path, "a") as f:
                    f.write(json.dumps(payload) + "\n")
            except OSError:
                pass
        if self.webhook_url:
            try:
                req = urllib.request.Request(
                    self.webhook_url, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                self._opener(req, timeout=2.0).read()
            except Exception:
                pass  # alert delivery is best-effort by design

    def _request_profile(self, rule: AlertRule, target,
                         now_mono: float,
                         alert_id: str | None = None) -> None:
        """Fire the PR-5 managed profiler on the offending target via
        its own ``POST /profile`` route — cooldown-limited so a bad
        hour cannot fill the fleet's disks with captures. The POST runs
        on its own thread: a slow target (exactly the kind that fires
        alerts) must not stall the evaluation loop behind its timeout."""
        if (self._last_profile_mono is not None
                and now_mono - self._last_profile_mono
                < self.profile_cooldown_s):
            return
        self._last_profile_mono = now_mono
        addr, host, gen = target.addr, target.host, target.gen

        def post():
            status = None
            try:
                req = urllib.request.Request(
                    f"http://{addr}/profile",
                    data=json.dumps(
                        {"seconds": self.profile_capture_s}).encode(),
                    headers={"Content-Type": "application/json"})
                status = self._opener(req, timeout=5.0).status
            except Exception as e:
                status = getattr(e, "code", None) or repr(e)
            detail = {"rule": rule.name, "host": host, "gen": gen,
                      "status": status}
            if alert_id is not None:
                detail["id"] = alert_id
            events_lib.emit("alert", "profile_requested", **detail)

        threading.Thread(target=post, daemon=True,
                         name=f"alert-profile-{host}").start()

    # ----------------------------------------------------------- evaluate
    def evaluate(self, collector) -> list[dict]:
        """One pass over every (rule, target) pair; returns the
        transition records of this tick (fired/resolved)."""
        now = time.monotonic()
        stale_after = (self.stale_after_s
                       if self.stale_after_s is not None
                       else collector.stale_after_s)
        transitions: list[dict] = list(self._eval_store(collector, now))
        for target in collector.targets:
            for rule in self.rules.values():
                if target.role not in rule.roles:
                    continue
                st = self._state(rule, target)
                if rule.kind == "anomaly":
                    transitions.extend(self._eval_anomaly(
                        rule, target, st, now))
                    continue
                cond, value, baseline = self._condition(
                    rule, target, now, stale_after)
                if cond is None:
                    continue
                if cond and not st.firing:
                    if (st.last_fire_mono is not None
                            and now - st.last_fire_mono < rule.cooldown_s):
                        continue  # re-fire inside the cooldown: suppress
                    transitions.append(self._transition(
                        rule, target, st, True, now, value, baseline))
                elif not cond and st.firing:
                    transitions.append(self._transition(
                        rule, target, st, False, now, value, baseline))
        # gauges reflect the post-evaluation truth for EVERY rule, 0s
        # included — a resolved alert must visibly go back to 0
        reg = get_registry()
        per_rule: dict[str, int] = {name: 0 for name in self.rules}
        for (rule_name, _r, _h), st in self._states.items():
            if st.firing and rule_name in per_rule:
                per_rule[rule_name] += 1
        for name, n in per_rule.items():
            reg.gauge("alerts_firing", labels={"rule": name},
                      help="targets currently firing each alert rule"
                      ).set(n)
        if self.slo_tracker is not None:
            try:
                # budget/burn gauges ride the evaluation cadence: the
                # metric catalog's slo_error_budget_remaining{slo=} and
                # slo_burn_rate{slo=,window=}
                self.slo_tracker.export_gauges()
            except Exception:
                pass  # accounting must never take the engine down
        return transitions

    def _eval_store(self, collector, now: float) -> list[dict]:
        """Evaluate ``store_degraded`` against the store_plane health
        machine (via ``collector.store_health()``) on the synthetic
        launcher/store target. Inert until some consumer has actually
        run store ops (``ops_total`` 0 = store-less deployment, not a
        healthy store). Side effect: latches ``_store_suppress`` so
        the same tick's ``fleet_stale`` evaluations are held — ALL
        hosts going quiet at once because the CONTROL plane died is a
        store outage, not a fleet of dead hosts."""
        rule = self.rules.get("store_degraded")
        self._store_suppress = False
        if rule is None:
            return []
        try:
            snap = collector.store_health()
        except Exception:
            return []
        if not isinstance(snap, dict) or not snap.get("ops_total"):
            return []
        value = {"ok": 0.0, "degraded": 1.0,
                 "down": 2.0}.get(snap.get("state"), 0.0)
        cond = value > (rule.above or 0.5)
        self._store_suppress = cond
        st = self._state(rule, _STORE_TARGET)
        if cond and not st.firing:
            if (st.last_fire_mono is not None
                    and now - st.last_fire_mono < rule.cooldown_s):
                return []
            return [self._transition(rule, _STORE_TARGET, st, True,
                                     now, value, rule.above)]
        if not cond and st.firing:
            return [self._transition(rule, _STORE_TARGET, st, False,
                                     now, value, rule.above)]
        return []

    def _condition(self, rule: AlertRule, target, now: float,
                   stale_after: float):
        """(cond, value, baseline) for the non-anomaly kinds; cond None
        = rule not applicable yet (missing input, never scraped)."""
        if rule.kind == "absence" and rule.name == "fleet_stale":
            if target.last_ok_mono is None:
                return None, None, None  # never scraped: not blamable
            if getattr(self, "_store_suppress", False):
                # store outage in progress: staleness evidence is
                # untrustworthy (the store IS the discovery plane and
                # the outage often stalls the whole control loop) —
                # hold fleet_stale in place, neither firing nor
                # resolving, until the store recovers
                return None, None, None
            age = now - target.last_ok_mono
            return age > stale_after, age, stale_after
        if rule.kind == "absence":  # trainer_step_stalled
            if (target.state(now, stale_after) != "ok"
                    or target.last_step_change_mono is None):
                return None, None, None
            idle = now - target.last_step_change_mono
            return idle > rule.for_s, idle, rule.for_s
        if rule.kind == "threshold":
            if rule.series == "device_mem_frac":
                value = target.device_mem_frac()
            else:
                value = target.memory.get(rule.series)
            if value is None:
                return None, None, None
            if rule.below is not None:
                return value < rule.below, value, rule.below
            return value > rule.above, value, rule.above
        if rule.kind == "burn_rate":
            tracker = self.slo_tracker
            if tracker is None or not rule.slo:
                return None, None, None
            key = f"{target.role}@{target.host}"
            try:
                short = tracker.burn_rate(rule.slo, key, rule.short_s)
                long_ = tracker.burn_rate(rule.slo, key, rule.long_s)
            except Exception:
                return None, None, None
            if short is None or long_ is None:
                return None, None, None  # no history yet: unknown
            # both windows must agree to fire; min() is therefore the
            # actionable burn, and its dropping below factor (the
            # short window recovering) resolves
            return (min(short, long_) >= rule.factor,
                    min(short, long_), rule.factor)
        if rule.kind == "rate":  # restart_churn over registry gens
            key = (target.role, target.host)
            seen = self._gen_seen.get(key)
            if seen is None:
                # First sight of this target: every generation already
                # in the registry is HISTORY, not churn — stamping them
                # "now" would false-fire every console (re)start against
                # a store that ever accumulated 3+ restarts. Only gens
                # appearing from here on count into the window.
                self._gen_seen[key] = {g: None for g in target.gens}
                return False, 0, rule.above
            for g in target.gens:
                seen.setdefault(g, now)
            recent = sum(1 for ts in seen.values()
                         if ts is not None and now - ts <= rule.for_s)
            return recent >= (rule.above or 1), recent, rule.above
        return None, None, None

    def _eval_anomaly(self, rule: AlertRule, target, st: _RuleState,
                      now: float) -> list[dict]:
        """Feed the detector every series sample newer than the last
        consumed one; spikes fire, ``resolve_after`` consecutive
        healthy samples resolve. Healthy-only window: a firing storm
        never drags its own baseline up."""
        out: list[dict] = []
        det = st.detector
        samples = [(ts, v) for ts, v in target.series.get(rule.series, ())
                   if st.last_sample_mono is None
                   or ts > st.last_sample_mono]
        if (not samples and st.firing and rule.quiet_resolve_s > 0
                and st.last_sample_mono is not None
                and now - st.last_sample_mono >= rule.quiet_resolve_s):
            # the series went quiet under a firing alert: no fresh
            # evidence can ever arrive to resolve it, and "no traffic"
            # is not the condition this rule alerts on — resolve so
            # downstream consumers (calm gates, pages) unwedge
            return [self._transition(
                rule, target, st, False, now, st.value,
                self._median(det))]
        for ts, value in samples:
            st.last_sample_mono = ts
            spike = det.is_spike(value) and self._directed(
                rule, det, value)
            if spike:
                st.healthy = 0
                st.value = value  # console shows the freshest reading
                if not st.firing:
                    if (st.last_fire_mono is not None
                            and now - st.last_fire_mono
                            < rule.cooldown_s):
                        continue
                    out.append(self._transition(
                        rule, target, st, True, now, value,
                        self._median(det)))
                continue
            det.add(value)
            if st.firing:
                st.healthy += 1
                st.value = value
                if st.healthy >= rule.resolve_after:
                    out.append(self._transition(
                        rule, target, st, False, now, value,
                        self._median(det)))
        return out

    def _directed(self, rule: AlertRule, det: SpikeDetector,
                  value: float) -> bool:
        med = self._median(det)
        if rule.min_abs and med is not None and (
                abs(value - med) < rule.min_abs):
            return False
        if rule.direction == "both" or med is None:
            return True
        if rule.direction == "above":
            return value > med
        return value < med

    @staticmethod
    def _median(det: SpikeDetector) -> float | None:
        xs = sorted(det.window)
        if not xs:
            return None
        n = len(xs)
        return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])
