"""Distributed request tracing: W3C-style context + tail-based sampling.

The span ring (obs/spans.py) answers "what was THIS process doing";
this module makes spans causal ACROSS processes, Dapper/OpenTelemetry
style, so one slow request can be followed from the router front
through a replica's admission gate, batcher queue, prefill slot and
per-decode quanta — and correlated with what the co-resident trainer
was doing at that (gen, step).

Three parts:

- **context** — :class:`TraceContext` (``trace_id``/``span_id``/
  ``sampled``) with a W3C-``traceparent``-shaped wire format
  (``00-<32hex>-<16hex>-<flags>``; flags bit 0 = "retain this trace
  unconditionally"). The router stamps (or honors) a context on every
  request; ``tools/serve_http.py`` continues it; every hop activates a
  :func:`spans.trace_scope` so ordinary ``span(...)`` calls become tree
  nodes. Serving-path code must reach contexts through
  :func:`continue_or_start` — minting a fresh id where an inbound
  context exists breaks the cross-process tree, and the
  ``trace-hygiene`` pass of ``python -m tools.analyze`` enforces it.

- **tail-based sampler** — keeping every decode-quantum span for every
  request is unaffordable, so completed traced spans buffer per
  trace_id in a bounded pending table and the retention decision runs
  at :meth:`Tracer.finish` (request end), when the tail is known: keep
  when the trace was *flagged* (hedged / failover / deadline / shed /
  leak / tail_latency — any incident a plane marked), *forced* (inbound
  sampled flag: how a router tells the hedge replica to retain), *slow*
  (``trace_keep_slow_ms``), or in the small random baseline
  (``trace_sample_pct``). Everything else is dropped. Every cap —
  pending-trace ring, spans-per-trace, spill-file bytes — drops loudly
  (``trace_dropped_total{where=}``).

- **spill** — retained trees append to per-host JSONL
  (``traces_<host>.jsonl``) beside the event journal, one JSON object
  per flush: ``{trace_id, host, gen, ts, reason, dur_ms, tags,
  spans:[{name, span_id, parent_id, t0, dur_s, thread, args}]}``.
  ``tags`` is the process's correlation snapshot (gen/step/
  weight_version). ``tools/timeline_report.py --trace <id>`` merges
  router + N replicas + trainer files into one Perfetto tree;
  ``tools/obs_report.py`` ranks the slowest retained traces.

A process may flush the same trace_id more than once (an in-process
router + replica each finish their own subtree); readers merge by
trace_id — span ids are globally unique, so concatenation is safe.

No jax at module scope (the obs/ package contract). Thread model: the
pending table has its own lock; file I/O runs under a separate lock and
never inside the pending lock (finish runs on request handler threads,
never under a service/scheduler lock).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import re
import threading
import time
from collections import OrderedDict

from pytorch_distributed_train_tpu.obs import spans as spans_lib

ENV_DIR = "PDTT_TRACE_DIR"
ENV_SAMPLE_PCT = "PDTT_TRACE_SAMPLE_PCT"
ENV_KEEP_SLOW_MS = "PDTT_TRACE_KEEP_SLOW_MS"

_WIRE = re.compile(r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """A position in a trace: the id plus the span new work should
    parent to. ``span_id`` None = a locally minted root (the first span
    opened under it becomes the tree root). ``sampled`` True = every
    process seeing this context must retain its subtree (the hedge /
    failover propagation bit)."""

    trace_id: str
    span_id: str | None = None
    sampled: bool = False


def new_trace_id() -> str:
    return spans_lib._rand_id(16)


def new_span_id() -> str:
    return spans_lib._rand_id(8)


def parse_traceparent(header: str | None) -> TraceContext | None:
    """``00-<trace>-<span>-<flags>`` → context, None for absent or
    malformed input (a bad client header must not 500 the router)."""
    if not header or not isinstance(header, str):
        return None
    m = _WIRE.match(header.strip().lower())
    if m is None:
        return None
    tid, sid, flags = m.groups()
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    try:
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    return TraceContext(tid, sid, sampled=sampled)


def format_traceparent(ctx: TraceContext) -> str:
    return (f"00-{ctx.trace_id}-{ctx.span_id or '0' * 16}-"
            f"{'01' if ctx.sampled else '00'}")


def start_trace() -> TraceContext:
    """Mint a fresh ROOT context. Request-path code must not call this
    where an inbound context may exist — use :func:`continue_or_start`;
    the ``trace-hygiene`` analyze pass enforces it for the serving
    surface."""
    return TraceContext(new_trace_id(), None)


def continue_or_start(inbound: str | None) -> TraceContext:
    """Honor an inbound ``traceparent`` (the one sanctioned way for the
    serving path to obtain a context) or mint a root when none came."""
    ctx = parse_traceparent(inbound)
    return ctx if ctx is not None else start_trace()


def activate(ctx: TraceContext):
    """Thread-scope context manager: spans opened inside carry the
    trace; the sampled flag is noted so a forced trace retains even if
    the local tail looks healthy."""
    if ctx.sampled:
        get_tracer().force(ctx.trace_id)
    return spans_lib.trace_scope(ctx.trace_id, ctx.span_id)


def current_child_context(sampled: bool = False) -> TraceContext | None:
    """Context for an OUTBOUND hop: the calling thread's open span
    becomes the remote side's parent. None when untraced or no span is
    open (nothing to parent to — don't fabricate lineage)."""
    tr = spans_lib.current_trace()
    if tr is None or tr[1] is None:
        return None
    return TraceContext(tr[0], tr[1], sampled=sampled)


def flag(trace_id: str, reason: str) -> None:
    get_tracer().flag(trace_id, reason)


def flag_current(reason: str) -> None:
    """Flag the calling thread's active trace (if any) for retention —
    what the shed/deadline/error paths call without needing the id."""
    tr = spans_lib.current_trace()
    if tr is not None:
        get_tracer().flag(tr[0], reason)


# --------------------------------------------------------------- sampler
class Tracer:
    """Per-process tail sampler + JSONL spill. One instance per process
    (module global below); every cap drops loudly."""

    def __init__(self, dir_path: str | None = None, *,
                 who: str | None = None, gen: str | None = None,
                 sample_pct: float | None = None,
                 keep_slow_ms: float | None = None,
                 max_pending: int = 256, max_spans_per_trace: int = 512,
                 max_file_mb: float = 64.0, rng=None):
        self.dir = dir_path
        self.who = who if who is not None else (
            f"host{os.environ.get('PROCESS_ID', '0')}")
        self.gen = gen if gen is not None else os.environ.get(
            "RESTART_GENERATION", "0")
        self.sample_pct = _env_float(ENV_SAMPLE_PCT, 0.0) \
            if sample_pct is None else float(sample_pct)
        self.keep_slow_ms = _env_float(ENV_KEEP_SLOW_MS, 250.0) \
            if keep_slow_ms is None else float(keep_slow_ms)
        self.max_pending = max(1, int(max_pending))
        self.max_spans_per_trace = max(1, int(max_spans_per_trace))
        self.max_file_bytes = int(max_file_mb * 1024 * 1024)
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()       # pending/flags tables
        self._io_lock = threading.Lock()    # spill file write+size
        self._pending: OrderedDict[str, list] = OrderedDict()
        # keep-reasons persist past the first finish (an in-process
        # router + replica both flush the same trace), bounded FIFO
        self._flags: OrderedDict[str, list[str]] = OrderedDict()
        self._forced: OrderedDict[str, bool] = OrderedDict()
        # traces already retained: spans completing AFTER their finish
        # (a hedge's slow loser attempt) flush as supplement records on
        # a later finish instead of rotting in pending
        self._retained: OrderedDict[str, str] = OrderedDict()
        self._fh = None
        self._size = 0
        self._failed = False

    @property
    def path(self) -> str | None:
        if not self.dir:
            return None
        return os.path.join(self.dir, f"traces_{self.who}.jsonl")

    # ------------------------------------------------------------ intake
    def add_span(self, sp) -> None:
        """Sink for completed traced spans (registered with spans.py at
        import). Buffers per trace; both caps drop loudly."""
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        get_registry().counter(
            "trace_spans_total",
            help="traced spans buffered by the tail sampler").inc()
        dropped: list[tuple[str, int]] = []
        with self._lock:
            spans = self._pending.get(sp.trace_id)
            if spans is None:
                if len(self._pending) >= self.max_pending:
                    # evict the oldest unfinished trace: an abandoned
                    # handler must not pin memory forever
                    _tid, old = self._pending.popitem(last=False)
                    dropped.append(("pending_ring", len(old)))
                spans = self._pending[sp.trace_id] = []
            if len(spans) >= self.max_spans_per_trace:
                dropped.append(("span_cap", 1))
            else:
                spans.append(sp)
        for where, n in dropped:
            self._count_drop(where, n)

    def _count_drop(self, where: str, n: int) -> None:
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        get_registry().counter(
            "trace_dropped_total", labels={"where": where},
            help="trace spans/trees dropped by the sampler's ring, "
                 "per-trace or spill-file caps").inc(n)

    def force(self, trace_id: str) -> None:
        """Inbound sampled flag: retain this trace unconditionally."""
        with self._lock:
            self._forced[trace_id] = True
            self._trim_marks()

    def flag(self, trace_id: str, reason: str) -> None:
        """Mark a trace for retention with an incident reason (hedged /
        failover / deadline / shed / leak / tail_latency / error)."""
        with self._lock:
            rs = self._flags.setdefault(trace_id, [])
            if reason not in rs:
                rs.append(reason)
            self._trim_marks()

    def _trim_marks(self) -> None:
        # flags/forced outlive finish() on purpose (multi-flush traces);
        # FIFO-bound them so an abandoned mark cannot leak
        while len(self._flags) > 4 * self.max_pending:
            self._flags.popitem(last=False)
        while len(self._forced) > 4 * self.max_pending:
            self._forced.popitem(last=False)

    # ----------------------------------------------------------- decision
    def finish(self, trace_id: str, dur_s: float | None = None,
               error: bool = False) -> str | None:
        """Close a trace locally: pop its buffered spans and decide
        retention now that the tail is known. Returns the keep reason
        (also the ``trace_sampled_total`` label), or None = dropped."""
        with self._lock:
            spans = self._pending.pop(trace_id, None) or []
            flags = list(self._flags.get(trace_id) or [])
            forced = self._forced.get(trace_id, False)
        reason = None
        if flags:
            reason = flags[0]
        elif error:
            reason = "error"
        elif forced:
            reason = "flag"
        elif (dur_s is not None and self.keep_slow_ms > 0
              and dur_s * 1e3 >= self.keep_slow_ms):
            reason = "slow"
        elif (self.sample_pct > 0
              and self._rng.random() * 100.0 < self.sample_pct):
            reason = "baseline"
        if reason is None or not spans:
            self._flush_late()
            return None
        from pytorch_distributed_train_tpu.obs.registry import get_registry

        get_registry().counter(
            "trace_sampled_total", labels={"reason": reason},
            help="trace trees retained by the tail sampler, by keep "
                 "reason").inc()
        with self._lock:
            self._retained[trace_id] = reason
            while len(self._retained) > 4 * self.max_pending:
                self._retained.popitem(last=False)
        self._spill(trace_id, reason, dur_s, spans, flags=flags)
        self._flush_late()
        return reason

    def _flush_late(self) -> None:
        """Spill pending spans of already-retained traces (a hedge's
        slow loser completes its attempt span after the winner's finish
        flushed the tree) as supplement records — merged by trace_id at
        read time, not re-counted."""
        with self._lock:
            late = [(tid, self._retained[tid], self._pending.pop(tid))
                    for tid in list(self._pending)
                    if tid in self._retained]
        for tid, reason, spans in late:
            if spans:
                self._spill(tid, reason, None, spans)

    # -------------------------------------------------------------- spill
    def _spill(self, trace_id: str, reason: str, dur_s: float | None,
               spans: list, flags: list[str] | None = None) -> None:
        if not self.dir or self._failed:
            return
        rec = {"trace_id": trace_id, "host": self.who, "gen": self.gen,
               "ts": time.time(), "reason": reason,
               # every incident mark, not just the primary: a request
               # that tripped the tail detector AND then 504'd carries
               # both, so readers can count by either
               "flags": list(flags) if flags else [reason],
               "dur_ms": (round(dur_s * 1e3, 3)
                          if dur_s is not None else None),
               "tags": spans_lib.correlation_tags(),
               "spans": [_span_dict(s) for s in spans]}
        try:
            line = json.dumps(rec, default=repr) + "\n"
        except (TypeError, ValueError):
            return
        data = line.encode("utf-8")
        with self._io_lock:
            try:
                if self._fh is None:
                    os.makedirs(self.dir, exist_ok=True)
                    self._fh = open(self.path, "ab")
                    self._size = os.path.getsize(self.path)
                if self._size + len(data) > self.max_file_bytes:
                    self._count_drop("file_cap", 1)
                    return
                self._fh.write(data)
                self._fh.flush()
                self._size += len(data)
            except OSError as e:
                self._failed = True
                print(f"[tracing] trace sink failed ({e}); further "
                      "retained traces counted but not persisted",
                      flush=True)

    def close(self) -> None:
        with self._io_lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def _span_dict(s) -> dict:
    d = {"name": s.name, "span_id": s.span_id, "parent_id": s.parent_id,
         "t0": s.t0, "dur_s": round(s.dur_s, 6), "thread": s.thread}
    if s.args:
        d["args"] = s.args
    if s.corr:
        d["corr"] = s.corr
    return d


def _env_float(var: str, default: float) -> float:
    try:
        return float(os.environ[var])
    except (KeyError, TypeError, ValueError):
        return default


# ------------------------------------------------------------ process-global
_GLOBAL: Tracer | None = None
_LOCK = threading.Lock()


def default_dir() -> str | None:
    """The spill directory when nothing configured one: $PDTT_TRACE_DIR,
    else a ``traces/`` sibling of the event journal's directory (the
    ISSUE contract: retained trees live beside the journal)."""
    d = os.environ.get(ENV_DIR)
    if d:
        return d
    ev = os.environ.get("PDTT_EVENTS_DIR")
    if ev:
        return os.path.join(os.path.dirname(ev.rstrip("/")), "traces")
    return None


def configure(dir_path: str | None, **kw) -> Tracer:
    """Install the process-global tracer (``dir_path`` None = decide and
    count but never spill). Reconfiguring closes the previous sink."""
    global _GLOBAL
    t = Tracer(dir_path, **kw)
    with _LOCK:
        prev, _GLOBAL = _GLOBAL, t
    if prev is not None:
        prev.close()
    return t


def get_tracer() -> Tracer:
    global _GLOBAL
    if _GLOBAL is None:
        with _LOCK:
            if _GLOBAL is None:
                _GLOBAL = Tracer(default_dir())
    return _GLOBAL


def _sink(sp) -> None:
    get_tracer().add_span(sp)


spans_lib.set_trace_sink(_sink)


def _reset_for_tests() -> None:
    global _GLOBAL
    with _LOCK:
        prev, _GLOBAL = _GLOBAL, None
    if prev is not None:
        prev.close()


# ---------------------------------------------------------------- readers
def load_traces(dir_path: str) -> list[dict]:
    """Every retained tree under ``dir_path`` (``traces_*.jsonl``),
    ts-sorted. Torn tail lines of a crashed writer are skipped. One
    trace_id may appear in several records (one per flushing process /
    subtree) — :func:`merge_trace` concatenates them."""
    import glob

    recs: list[dict] = []
    for path in sorted(glob.glob(os.path.join(dir_path,
                                              "traces_*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("trace_id"):
                        recs.append(rec)
        except OSError:
            continue
    recs.sort(key=lambda r: r.get("ts", 0.0))
    return recs


def merge_trace(trees: list[dict], trace_id: str) -> list[dict]:
    """All spans of one trace across every flushed record, each span
    annotated with its writer's ``host``/``reason``/``tags``, t0-sorted.
    ``trace_id`` may be a unique prefix (the ids are long)."""
    full = {t["trace_id"] for t in trees
            if t["trace_id"].startswith(trace_id)}
    if len(full) > 1:
        raise ValueError(
            f"trace id prefix {trace_id!r} is ambiguous ({len(full)} "
            "matches)")
    out: list[dict] = []
    for t in trees:
        if not t["trace_id"].startswith(trace_id):
            continue
        for s in t.get("spans") or []:
            s = dict(s)
            s["host"] = t.get("host")
            s["reason"] = t.get("reason")
            s["tags"] = t.get("tags") or {}
            out.append(s)
    out.sort(key=lambda s: s.get("t0", 0.0))
    return out
