"""Checkpoint interop: flax params ↔ torch-layout safetensors.

SURVEY hard part #2: the reference promises "the same config and checkpoint
interface" — a torch user must be able to read our weights and vice versa.
Orbax remains the native training checkpoint (sharded, async — SURVEY §5.4);
this module is the BRIDGE format: a single safetensors file whose tensors
use torch conventions so `safetensors.torch.load_file` yields a plain
state_dict:

- names: '/'-joined flax paths → dotted; ``kernel``→``weight``,
  ``scale``→``weight``, ``embedding``→``weight``, ``bias`` stays
  (torch:serialization.py state_dict naming, nn.Linear/Conv2d/LayerNorm).
- layouts: Dense (in, out) → Linear (out, in); Conv HWIO → Conv2d OIHW;
  DenseGeneral 3-D kernels flatten their head dims then transpose like a
  Linear (matching how HF exports fused attention projections).

Every transform is recorded in the safetensors metadata header, so
``load_flax_safetensors`` inverts the export EXACTLY (lossless round-trip)
without re-deriving model structure — foreign checkpoints with torch names
import through the same inverse as long as shapes match the template tree.
"""

from __future__ import annotations

import json
from typing import Any

import jax
import numpy as np


def _path_str(path) -> str:
    from pytorch_distributed_train_tpu.parallel.partition import path_name

    return path_name(path)


def _plan(name: str, shape: tuple[int, ...]) -> tuple[str, str]:
    """(flax path, shape) → (torch state_dict name, transform tag)."""
    parts = name.split("/")
    leaf = parts[-1]
    transform = "none"
    if leaf == "kernel":
        torch_leaf = "weight"
        if len(shape) == 2:
            transform = "dense_T"  # (in, out) → (out, in)
        elif len(shape) == 4:
            transform = "conv_oihw"  # HWIO → OIHW
        elif len(shape) == 3:
            # DenseGeneral. Output-fused (in, h, d) flattens the head dims;
            # input-fused (h, d, out) — the o_proj orientation — flattens
            # the first two. The metadata-recorded original shape makes the
            # inverse exact either way.
            if name.endswith(("o_proj/kernel", "attn_out/kernel",
                              "attn/c_proj/kernel")):
                transform = "dgen_in3"  # (h, d, out) → (out, h·d)
            else:
                transform = "dgen_out3"  # (in, h, d) → (h·d, in)
    elif leaf in ("scale", "embedding"):
        torch_leaf = "weight"
    else:
        torch_leaf = leaf
    torch_name = (".".join(parts[:-1] + [torch_leaf])
                  if len(parts) > 1 else torch_leaf)
    return torch_name, transform


def _to_torch(arr: np.ndarray, transform: str) -> np.ndarray:
    if transform == "dense_T":
        arr = arr.T
    elif transform == "conv_oihw":
        arr = arr.transpose(3, 2, 0, 1)
    elif transform == "dgen_in3":
        arr = arr.reshape(-1, arr.shape[2]).T
    elif transform == "dgen_out3":
        arr = arr.reshape(arr.shape[0], -1).T
    return np.ascontiguousarray(arr)


def _from_torch(arr: np.ndarray, transform: str,
                shape: tuple[int, ...]) -> np.ndarray:
    if transform == "dense_T":
        out = arr.T
    elif transform == "conv_oihw":
        out = arr.transpose(2, 3, 1, 0)
    elif transform in ("dgen_in3", "dgen_out3"):
        out = arr.T.reshape(shape)
    else:
        out = arr.reshape(shape)
    return np.ascontiguousarray(out)


def save_torch_safetensors(params: Any, path: str) -> None:
    """Export a flax param tree as a torch-state_dict-style safetensors file."""
    from safetensors.numpy import save_file

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    tensors: dict[str, np.ndarray] = {}
    metas: dict[str, dict] = {}
    for p, leaf in flat:
        name = _path_str(p)
        arr = np.asarray(jax.device_get(leaf))
        tname, transform = _plan(name, arr.shape)
        if tname in tensors:
            raise ValueError(f"torch name collision: {tname}")
        tensors[tname] = _to_torch(arr, transform)
        metas[tname] = {"flax_name": name, "shape": list(arr.shape),
                        "transform": transform}
    save_file(tensors, path, metadata={"interop": json.dumps(metas)})


def load_flax_safetensors(path: str, template: Any) -> Any:
    """Import a (torch-layout) safetensors file into ``template``'s tree
    structure. ``template`` may hold arrays or ShapeDtypeStructs — only
    shapes/dtypes are read. Uses the export metadata when present; foreign
    torch files fall back to the template-derived plan."""
    from safetensors import safe_open

    with safe_open(path, framework="numpy") as f:
        file_meta = f.metadata() or {}
        metas = json.loads(file_meta.get("interop", "{}"))
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        leaves = []
        for p, leaf in flat:
            name = _path_str(p)
            shape = tuple(leaf.shape)
            tname, transform = _plan(name, shape)
            meta = metas.get(tname)
            if meta is not None:
                transform = meta["transform"]
                shape = tuple(meta["shape"])
            arr = _from_torch(f.get_tensor(tname), transform, shape)
            if arr.shape != tuple(leaf.shape):
                raise ValueError(
                    f"{tname}: restored shape {arr.shape} != template "
                    f"{tuple(leaf.shape)}"
                )
            leaves.append(arr.astype(np.dtype(leaf.dtype)))
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ----------------------------------------------------- HF transformers bridge
#
# Name-level mapping to the HuggingFace state_dict conventions for the LM
# families, so checkpoints are mutually legible with the torch ecosystem the
# reference lives in (SURVEY hard part #2): our Llama ↔ HF LlamaForCausalLM,
# our BERT MLM ↔ HF BertForMaskedLM. Layout notes: flax Dense kernels are
# (in, out) vs torch Linear (out, in); DenseGeneral attention projections
# carry explicit (heads, head_dim) axes that HF fuses into one dim. Our RoPE
# uses the halves ("rotate_half") convention — the same as HF's modeling
# code, so q/k projections map with NO permutation (unlike Meta→HF
# conversion, which must interleave).
#
# Transform tags reuse the generic bridge's vocabulary (_to_torch /
# _from_torch): dense_T (in,out)→(out,in), dgen_out3 (C,H,D)→(H·D,C),
# dgen_in3 (H,D,C)→(C,H·D); plus HF-only "flat" (squeeze/flatten to the HF
# shape) and "none".

_HF_RULES: dict[str, list[tuple[str, str, str]]] = {
    "llama": [
        (r"^tok_embed/embedding$", "model.embed_tokens.weight", "none"),
        (r"^layer(\d+)/attn/(q_proj|k_proj|v_proj)/kernel$",
         "model.layers.{0}.self_attn.{1}.weight", "dgen_out3"),
        (r"^layer(\d+)/attn/o_proj/kernel$",
         "model.layers.{0}.self_attn.o_proj.weight", "dgen_in3"),
        (r"^layer(\d+)/mlp/(gate_proj|up_proj|down_proj)/kernel$",
         "model.layers.{0}.mlp.{1}.weight", "dense_T"),
        (r"^layer(\d+)/input_norm/scale$",
         "model.layers.{0}.input_layernorm.weight", "none"),
        (r"^layer(\d+)/post_attn_norm/scale$",
         "model.layers.{0}.post_attention_layernorm.weight", "none"),
        (r"^final_norm/scale$", "model.norm.weight", "none"),
        (r"^lm_head/kernel$", "lm_head.weight", "dense_T"),
    ],
    # GPT-2 note: HF stores linear layers as Conv1D with (in, out) weights —
    # the SAME orientation as flax Dense, so 2-D kernels map with NO
    # transpose ("none"); 3-D DenseGeneral kernels flatten head dims without
    # transposing (conv1d_out3 / conv1d_in3). The fused c_attn is assembled
    # from q/k/v in to_hf_state_dict (and split in from_hf_state_dict).
    "gpt2": [
        (r"^wte/embedding$", "transformer.wte.weight", "none"),
        (r"^wpe$", "transformer.wpe.weight", "none"),
        (r"^h(\d+)/(ln_1|ln_2)/scale$", "transformer.h.{0}.{1}.weight",
         "none"),
        (r"^h(\d+)/(ln_1|ln_2)/bias$", "transformer.h.{0}.{1}.bias", "none"),
        (r"^h(\d+)/attn/(q_proj|k_proj|v_proj)/kernel$",
         "__qkv__.{0}.{1}.weight", "conv1d_out3"),
        (r"^h(\d+)/attn/(q_proj|k_proj|v_proj)/bias$",
         "__qkv__.{0}.{1}.bias", "flat"),
        (r"^h(\d+)/attn/c_proj/kernel$",
         "transformer.h.{0}.attn.c_proj.weight", "conv1d_in3"),
        (r"^h(\d+)/attn/c_proj/bias$",
         "transformer.h.{0}.attn.c_proj.bias", "none"),
        (r"^h(\d+)/c_fc/kernel$", "transformer.h.{0}.mlp.c_fc.weight",
         "none"),
        (r"^h(\d+)/c_fc/bias$", "transformer.h.{0}.mlp.c_fc.bias", "none"),
        (r"^h(\d+)/c_proj/kernel$", "transformer.h.{0}.mlp.c_proj.weight",
         "none"),
        (r"^h(\d+)/c_proj/bias$", "transformer.h.{0}.mlp.c_proj.bias",
         "none"),
        (r"^ln_f/scale$", "transformer.ln_f.weight", "none"),
        (r"^ln_f/bias$", "transformer.ln_f.bias", "none"),
    ],
    # T5 note: block layout is positional in HF — layer.0 = self-attn,
    # layer.1 = cross-attn (decoder) or FF (encoder), layer.2 = FF
    # (decoder). The relative-bias table exists in block 0 only (one per
    # stack). encoder/decoder.embed_tokens aliases of `shared` are emitted
    # in to_hf_state_dict.
    "t5": [
        (r"^shared/embedding$", "shared.weight", "none"),
        (r"^enc_block(\d+)/self_attn/(q|k|v)_proj/kernel$",
         "encoder.block.{0}.layer.0.SelfAttention.{1}.weight", "dgen_out3"),
        (r"^enc_block(\d+)/self_attn/o_proj/kernel$",
         "encoder.block.{0}.layer.0.SelfAttention.o.weight", "dgen_in3"),
        (r"^enc_block0/self_attn/rel_bias/embedding$",
         "encoder.block.0.layer.0.SelfAttention"
         ".relative_attention_bias.weight", "none"),
        (r"^enc_block(\d+)/ln_self/scale$",
         "encoder.block.{0}.layer.0.layer_norm.weight", "none"),
        (r"^enc_block(\d+)/mlp/wi/kernel$",
         "encoder.block.{0}.layer.1.DenseReluDense.wi.weight", "dense_T"),
        (r"^enc_block(\d+)/mlp/wo/kernel$",
         "encoder.block.{0}.layer.1.DenseReluDense.wo.weight", "dense_T"),
        (r"^enc_block(\d+)/ln_mlp/scale$",
         "encoder.block.{0}.layer.1.layer_norm.weight", "none"),
        (r"^enc_final_norm/scale$", "encoder.final_layer_norm.weight",
         "none"),
        (r"^dec_block(\d+)/self_attn/(q|k|v)_proj/kernel$",
         "decoder.block.{0}.layer.0.SelfAttention.{1}.weight", "dgen_out3"),
        (r"^dec_block(\d+)/self_attn/o_proj/kernel$",
         "decoder.block.{0}.layer.0.SelfAttention.o.weight", "dgen_in3"),
        (r"^dec_block0/self_attn/rel_bias/embedding$",
         "decoder.block.0.layer.0.SelfAttention"
         ".relative_attention_bias.weight", "none"),
        (r"^dec_block(\d+)/ln_self/scale$",
         "decoder.block.{0}.layer.0.layer_norm.weight", "none"),
        (r"^dec_block(\d+)/cross_attn/(q|k|v)_proj/kernel$",
         "decoder.block.{0}.layer.1.EncDecAttention.{1}.weight",
         "dgen_out3"),
        (r"^dec_block(\d+)/cross_attn/o_proj/kernel$",
         "decoder.block.{0}.layer.1.EncDecAttention.o.weight", "dgen_in3"),
        (r"^dec_block(\d+)/ln_cross/scale$",
         "decoder.block.{0}.layer.1.layer_norm.weight", "none"),
        (r"^dec_block(\d+)/mlp/wi/kernel$",
         "decoder.block.{0}.layer.2.DenseReluDense.wi.weight", "dense_T"),
        (r"^dec_block(\d+)/mlp/wo/kernel$",
         "decoder.block.{0}.layer.2.DenseReluDense.wo.weight", "dense_T"),
        (r"^dec_block(\d+)/ln_mlp/scale$",
         "decoder.block.{0}.layer.2.layer_norm.weight", "none"),
        (r"^dec_final_norm/scale$", "decoder.final_layer_norm.weight",
         "none"),
        (r"^lm_head/kernel$", "lm_head.weight", "dense_T"),
    ],
    "vit": [
        (r"^patch_embed/kernel$",
         "vit.embeddings.patch_embeddings.projection.weight", "conv_oihw"),
        (r"^patch_embed/bias$",
         "vit.embeddings.patch_embeddings.projection.bias", "none"),
        (r"^cls_token$", "vit.embeddings.cls_token", "none"),
        (r"^pos_embed$", "vit.embeddings.position_embeddings", "none"),
        (r"^block(\d+)/attn/(query|key|value)/kernel$",
         "vit.encoder.layer.{0}.attention.attention.{1}.weight", "dgen_out3"),
        (r"^block(\d+)/attn/(query|key|value)/bias$",
         "vit.encoder.layer.{0}.attention.attention.{1}.bias", "flat"),
        (r"^block(\d+)/attn/attn_out/kernel$",
         "vit.encoder.layer.{0}.attention.output.dense.weight", "dgen_in3"),
        (r"^block(\d+)/attn/attn_out/bias$",
         "vit.encoder.layer.{0}.attention.output.dense.bias", "none"),
        (r"^block(\d+)/ln1/scale$",
         "vit.encoder.layer.{0}.layernorm_before.weight", "none"),
        (r"^block(\d+)/ln1/bias$",
         "vit.encoder.layer.{0}.layernorm_before.bias", "none"),
        (r"^block(\d+)/ln2/scale$",
         "vit.encoder.layer.{0}.layernorm_after.weight", "none"),
        (r"^block(\d+)/ln2/bias$",
         "vit.encoder.layer.{0}.layernorm_after.bias", "none"),
        (r"^block(\d+)/mlp/mlp_in/kernel$",
         "vit.encoder.layer.{0}.intermediate.dense.weight", "dense_T"),
        (r"^block(\d+)/mlp/mlp_in/bias$",
         "vit.encoder.layer.{0}.intermediate.dense.bias", "none"),
        (r"^block(\d+)/mlp/mlp_out/kernel$",
         "vit.encoder.layer.{0}.output.dense.weight", "dense_T"),
        (r"^block(\d+)/mlp/mlp_out/bias$",
         "vit.encoder.layer.{0}.output.dense.bias", "none"),
        (r"^ln_final/scale$", "vit.layernorm.weight", "none"),
        (r"^ln_final/bias$", "vit.layernorm.bias", "none"),
        (r"^head/kernel$", "classifier.weight", "dense_T"),
        (r"^head/bias$", "classifier.bias", "none"),
    ],
    "bert": [
        (r"^word_embed/embedding$",
         "bert.embeddings.word_embeddings.weight", "none"),
        (r"^pos_embed$", "bert.embeddings.position_embeddings.weight", "flat"),
        (r"^type_embed/embedding$",
         "bert.embeddings.token_type_embeddings.weight", "none"),
        (r"^embed_ln/scale$", "bert.embeddings.LayerNorm.weight", "none"),
        (r"^embed_ln/bias$", "bert.embeddings.LayerNorm.bias", "none"),
        (r"^layer(\d+)/attn/(query|key|value)/kernel$",
         "bert.encoder.layer.{0}.attention.self.{1}.weight", "dgen_out3"),
        (r"^layer(\d+)/attn/(query|key|value)/bias$",
         "bert.encoder.layer.{0}.attention.self.{1}.bias", "flat"),
        (r"^layer(\d+)/attn/attn_out/kernel$",
         "bert.encoder.layer.{0}.attention.output.dense.weight", "dgen_in3"),
        (r"^layer(\d+)/attn/attn_out/bias$",
         "bert.encoder.layer.{0}.attention.output.dense.bias", "none"),
        (r"^layer(\d+)/ln_attn/scale$",
         "bert.encoder.layer.{0}.attention.output.LayerNorm.weight", "none"),
        (r"^layer(\d+)/ln_attn/bias$",
         "bert.encoder.layer.{0}.attention.output.LayerNorm.bias", "none"),
        (r"^layer(\d+)/mlp_in/kernel$",
         "bert.encoder.layer.{0}.intermediate.dense.weight", "dense_T"),
        (r"^layer(\d+)/mlp_in/bias$",
         "bert.encoder.layer.{0}.intermediate.dense.bias", "none"),
        (r"^layer(\d+)/mlp_out/kernel$",
         "bert.encoder.layer.{0}.output.dense.weight", "dense_T"),
        (r"^layer(\d+)/mlp_out/bias$",
         "bert.encoder.layer.{0}.output.dense.bias", "none"),
        (r"^layer(\d+)/ln_mlp/scale$",
         "bert.encoder.layer.{0}.output.LayerNorm.weight", "none"),
        (r"^layer(\d+)/ln_mlp/bias$",
         "bert.encoder.layer.{0}.output.LayerNorm.bias", "none"),
        (r"^mlm_dense/kernel$",
         "cls.predictions.transform.dense.weight", "dense_T"),
        (r"^mlm_dense/bias$", "cls.predictions.transform.dense.bias", "none"),
        (r"^mlm_ln/scale$",
         "cls.predictions.transform.LayerNorm.weight", "none"),
        (r"^mlm_ln/bias$", "cls.predictions.transform.LayerNorm.bias", "none"),
        (r"^mlm_bias$", "cls.predictions.bias", "none"),
    ],
}


def _hf_rules(family: str):
    import re as _re

    for prefix, rules in _HF_RULES.items():
        if family.startswith(prefix):
            return [(_re.compile(pat), fmt, tr) for pat, fmt, tr in rules]
    raise KeyError(f"no HF mapping for model family {family!r} "
                   f"(have {sorted(_HF_RULES)})")


def _hf_name(name: str, rules) -> tuple[str, str]:
    for pat, fmt, tr in rules:
        m = pat.match(name)
        if m:
            return fmt.format(*m.groups()), tr
    raise KeyError(f"param {name!r} has no HF mapping rule")


def to_hf_state_dict(params: Any, family: str) -> dict[str, np.ndarray]:
    """Flax param tree → HF-convention numpy state dict.

    For BERT the tied decoder entries (``cls.predictions.decoder.*``) are
    emitted too, so ``BertForMaskedLM.load_state_dict`` is satisfied without
    relying on HF's tying hooks.
    """
    rules = _hf_rules(family)
    out: dict[str, np.ndarray] = {}
    for p, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = _path_str(p)
        hf, tr = _hf_name(name, rules)
        arr = np.asarray(jax.device_get(leaf))
        if tr == "flat":
            # pos_embed (1,L,C) → (L,C); fused (H,D) biases → (H·D,)
            arr = arr[0] if (arr.ndim == 3 and arr.shape[0] == 1) else arr.reshape(-1)
            arr = np.ascontiguousarray(arr)
        elif tr == "conv1d_out3":  # (C,H,D) → (C,H·D), no transpose (Conv1D)
            arr = np.ascontiguousarray(arr.reshape(arr.shape[0], -1))
        elif tr == "conv1d_in3":   # (H,D,C) → (H·D,C), no transpose (Conv1D)
            arr = np.ascontiguousarray(arr.reshape(-1, arr.shape[-1]))
        else:
            arr = _to_torch(arr, tr)
        out[hf] = arr
    if family.startswith("bert"):
        out["cls.predictions.decoder.weight"] = out[
            "bert.embeddings.word_embeddings.weight"]
        out["cls.predictions.decoder.bias"] = out["cls.predictions.bias"]
    if family.startswith("gpt2"):
        _gpt2_fuse_qkv(out)
        out["lm_head.weight"] = out["transformer.wte.weight"]  # tied
    if family.startswith("t5"):
        # HF T5 state dicts carry the shared table under the per-stack
        # embed_tokens aliases too; a tied model (no lm_head param —
        # ModelConfig.tie_word_embeddings) aliases the head as well.
        out["encoder.embed_tokens.weight"] = out["shared.weight"]
        out["decoder.embed_tokens.weight"] = out["shared.weight"]
        if "lm_head.weight" not in out:
            out["lm_head.weight"] = out["shared.weight"]
    return out


def _gpt2_fuse_qkv(out: dict) -> None:
    """Assemble HF GPT-2's fused c_attn from the staged q/k/v entries."""
    import re as _re

    layers = sorted({int(m.group(1)) for k in out
                     if (m := _re.match(r"__qkv__\.(\d+)\.", k))})
    for i in layers:
        w = [out.pop(f"__qkv__.{i}.{p}.weight")
             for p in ("q_proj", "k_proj", "v_proj")]
        b = [out.pop(f"__qkv__.{i}.{p}.bias")
             for p in ("q_proj", "k_proj", "v_proj")]
        out[f"transformer.h.{i}.attn.c_attn.weight"] = np.concatenate(w, 1)
        out[f"transformer.h.{i}.attn.c_attn.bias"] = np.concatenate(b, 0)


def from_hf_state_dict(state_dict: dict, template: Any, family: str) -> Any:
    """HF-convention state dict (numpy or torch tensors) → flax param tree
    shaped like ``template``."""
    import re as _re

    rules = _hf_rules(family)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for p, leaf in flat:
        name = _path_str(p)
        hf, tr = _hf_name(name, rules)
        qkv = _re.match(r"__qkv__\.(\d+)\.(q_proj|k_proj|v_proj)\.(\w+)", hf)
        if qkv:  # gpt2: slice the fused c_attn third for this projection
            i, proj, kind = qkv.groups()
            fused = state_dict[f"transformer.h.{i}.attn.c_attn.{kind}"]
            if hasattr(fused, "detach"):
                fused = fused.detach().cpu().numpy()
            fused = np.asarray(fused)
            C3 = fused.shape[-1]
            j = ("q_proj", "k_proj", "v_proj").index(proj)
            arr = fused[..., j * C3 // 3:(j + 1) * C3 // 3]
        else:
            arr = state_dict[hf]
            if hasattr(arr, "detach"):  # torch tensor
                arr = arr.detach().cpu().numpy()
            arr = np.asarray(arr)
        shape = tuple(leaf.shape)
        arr = (arr.reshape(shape)
               if tr in ("flat", "conv1d_out3", "conv1d_in3")
               else _from_torch(arr, tr, shape))
        if arr.shape != shape:
            raise ValueError(f"{hf}: shape {arr.shape} != template {shape}")
        leaves.append(np.ascontiguousarray(arr).astype(np.dtype(leaf.dtype)))
    return jax.tree_util.tree_unflatten(treedef, leaves)
