"""Checkpoint interop: flax params ↔ torch-layout safetensors.

SURVEY hard part #2: the reference promises "the same config and checkpoint
interface" — a torch user must be able to read our weights and vice versa.
Orbax remains the native training checkpoint (sharded, async — SURVEY §5.4);
this module is the BRIDGE format: a single safetensors file whose tensors
use torch conventions so `safetensors.torch.load_file` yields a plain
state_dict:

- names: '/'-joined flax paths → dotted; ``kernel``→``weight``,
  ``scale``→``weight``, ``embedding``→``weight``, ``bias`` stays
  (torch:serialization.py state_dict naming, nn.Linear/Conv2d/LayerNorm).
- layouts: Dense (in, out) → Linear (out, in); Conv HWIO → Conv2d OIHW;
  DenseGeneral 3-D kernels flatten their head dims then transpose like a
  Linear (matching how HF exports fused attention projections).

Every transform is recorded in the safetensors metadata header, so
``load_flax_safetensors`` inverts the export EXACTLY (lossless round-trip)
without re-deriving model structure — foreign checkpoints with torch names
import through the same inverse as long as shapes match the template tree.
"""

from __future__ import annotations

import json
from typing import Any

import jax
import numpy as np


def _path_str(path) -> str:
    from pytorch_distributed_train_tpu.parallel.partition import path_name

    return path_name(path)


def _plan(name: str, shape: tuple[int, ...]) -> tuple[str, str]:
    """(flax path, shape) → (torch state_dict name, transform tag)."""
    parts = name.split("/")
    leaf = parts[-1]
    transform = "none"
    if leaf == "kernel":
        torch_leaf = "weight"
        if len(shape) == 2:
            transform = "dense_T"  # (in, out) → (out, in)
        elif len(shape) == 4:
            transform = "conv_oihw"  # HWIO → OIHW
        elif len(shape) == 3:
            # DenseGeneral. Output-fused (in, h, d) flattens the head dims;
            # input-fused (h, d, out) — the o_proj orientation — flattens
            # the first two. The metadata-recorded original shape makes the
            # inverse exact either way.
            if name.endswith(("o_proj/kernel", "attn_out/kernel")):
                transform = "dgen_in3"  # (h, d, out) → (out, h·d)
            else:
                transform = "dgen_out3"  # (in, h, d) → (h·d, in)
    elif leaf in ("scale", "embedding"):
        torch_leaf = "weight"
    else:
        torch_leaf = leaf
    torch_name = (".".join(parts[:-1] + [torch_leaf])
                  if len(parts) > 1 else torch_leaf)
    return torch_name, transform


def _to_torch(arr: np.ndarray, transform: str) -> np.ndarray:
    if transform == "dense_T":
        arr = arr.T
    elif transform == "conv_oihw":
        arr = arr.transpose(3, 2, 0, 1)
    elif transform == "dgen_in3":
        arr = arr.reshape(-1, arr.shape[2]).T
    elif transform == "dgen_out3":
        arr = arr.reshape(arr.shape[0], -1).T
    return np.ascontiguousarray(arr)


def _from_torch(arr: np.ndarray, transform: str,
                shape: tuple[int, ...]) -> np.ndarray:
    if transform == "dense_T":
        out = arr.T
    elif transform == "conv_oihw":
        out = arr.transpose(2, 3, 1, 0)
    elif transform in ("dgen_in3", "dgen_out3"):
        out = arr.T.reshape(shape)
    else:
        out = arr.reshape(shape)
    return np.ascontiguousarray(out)


def save_torch_safetensors(params: Any, path: str) -> None:
    """Export a flax param tree as a torch-state_dict-style safetensors file."""
    from safetensors.numpy import save_file

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    tensors: dict[str, np.ndarray] = {}
    metas: dict[str, dict] = {}
    for p, leaf in flat:
        name = _path_str(p)
        arr = np.asarray(jax.device_get(leaf))
        tname, transform = _plan(name, arr.shape)
        if tname in tensors:
            raise ValueError(f"torch name collision: {tname}")
        tensors[tname] = _to_torch(arr, transform)
        metas[tname] = {"flax_name": name, "shape": list(arr.shape),
                        "transform": transform}
    save_file(tensors, path, metadata={"interop": json.dumps(metas)})


def load_flax_safetensors(path: str, template: Any) -> Any:
    """Import a (torch-layout) safetensors file into ``template``'s tree
    structure. ``template`` may hold arrays or ShapeDtypeStructs — only
    shapes/dtypes are read. Uses the export metadata when present; foreign
    torch files fall back to the template-derived plan."""
    from safetensors import safe_open

    with safe_open(path, framework="numpy") as f:
        file_meta = f.metadata() or {}
        metas = json.loads(file_meta.get("interop", "{}"))
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        leaves = []
        for p, leaf in flat:
            name = _path_str(p)
            shape = tuple(leaf.shape)
            tname, transform = _plan(name, shape)
            meta = metas.get(tname)
            if meta is not None:
                transform = meta["transform"]
                shape = tuple(meta["shape"])
            arr = _from_torch(f.get_tensor(tname), transform, shape)
            if arr.shape != tuple(leaf.shape):
                raise ValueError(
                    f"{tname}: restored shape {arr.shape} != template "
                    f"{tuple(leaf.shape)}"
                )
            leaves.append(arr.astype(np.dtype(leaf.dtype)))
    return jax.tree_util.tree_unflatten(treedef, leaves)
